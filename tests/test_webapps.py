"""Web-app layer tests: central dashboard API + jupyter-web-app backend.

Mirrors the reference's HTTP-level API tests with a mocked MetricsService
(centraldashboard app/api_test.ts:30-99) and the jupyter-web-app CRUD
surface (kubeflow_jupyter/common/api.py:30-191), driven over real HTTP
against the in-memory cluster.
"""

import json
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.controllers import build_manager
from kubeflow_tpu.webapps.dashboard import (DashboardServer, MetricsService,
                                            build_dashboard_app)
from kubeflow_tpu.webapps.jupyter import (JupyterWebApp,
                                          build_notebook_manifest)
from kubeflow_tpu.webapps._http import ApiError


def get_json(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


def post_json(url, payload, method="POST"):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method=method,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


@pytest.fixture
def cluster():
    c = FakeCluster()
    c.add_node("cpu-0", {"cpu": 96, "memory": 2 ** 36})
    c.add_tpu_slice_nodes("v5e-8")
    for ns in ("kubeflow", "alice"):
        c.create({"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": ns}})
    return c


class TestDashboard:
    def test_namespaces_and_tpu_slices(self, cluster):
        server = DashboardServer(cluster)
        port = server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            names = get_json(f"{base}/api/namespaces")
            assert "kubeflow" in names and "alice" in names
            slices = get_json(f"{base}/api/tpu/slices")
            assert len(slices) == 1
            assert slices[0]["topology"] == "v5e-8"
            assert slices[0]["chips"] == 8
            assert slices[0]["hosts"] == 2
        finally:
            server.stop()

    def test_index_page_served(self, cluster):
        server = DashboardServer(cluster)
        port = server.start()
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as r:
                assert r.headers["Content-Type"].startswith("text/html")
                html = r.read().decode()
            # the SPA shell: selector + routed views + app bundle
            assert 'id="ns-selector"' in html
            assert 'data-view="activities"' in html
            assert '<script src="app.js">' in html
        finally:
            server.stop()

    def test_spa_bundle_served(self, cluster):
        server = DashboardServer(cluster)
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/app.js") as r:
                assert r.headers["Content-Type"].startswith(
                    "application/javascript")
                js = r.read().decode()
            # the SPA consumes the dashboard API, iframes jupyter, and
            # bounces 401s through the gatekeeper login page
            for needle in ("api/namespaces", "api/tpu/slices",
                           "api/activities/", "api/metrics/",
                           "jupyter-frame", 'LOGIN_PATH = "/login"',
                           "status === 401"):
                assert needle in js, needle
        finally:
            server.stop()

    def test_env_info_identity_and_platform(self, cluster):
        """/api/env-info (api.ts router): email comes from the identity
        header the auth ingress injects (IAP prefix stripped), provider
        from Node providerID, version from the Application CR when one
        exists, else the package version."""
        cluster.create({
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": "gce-0"},
            "spec": {"providerID": "gce://proj/us-central1-a/vm-0"}})
        server = DashboardServer(cluster)
        port = server.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/env-info",
                headers={"x-goog-authenticated-user-email":
                         "accounts.google.com:alice@example.com"})
            with urllib.request.urlopen(req) as r:
                env = json.loads(r.read())
            assert env["user"]["email"] == "alice@example.com"
            assert env["platform"]["providerName"] == "gce"
            from kubeflow_tpu import __version__
            assert env["platform"]["kubeflowVersion"] == __version__
            # anonymous without the header (no ingress in front)
            anon = get_json(f"http://127.0.0.1:{port}/api/env-info")
            assert anon["user"]["email"] == "anonymous@kubeflow.org"
        finally:
            server.stop()

    def test_sidebar_links_match_registered_views(self):
        """Every data-view link in the shell has a registered view in
        the bundle and vice versa — a link without a view silently falls
        back to overview, which this pins against."""
        import re
        from kubeflow_tpu.webapps.dashboard import INDEX_HTML, _read_app_js
        links = set(re.findall(r'data-view="(\w+)"', INDEX_HTML))
        views_block = re.search(r"const VIEWS = \{(.*?)\};", _read_app_js(),
                                re.S).group(1)
        views = set(re.findall(r"(\w+):\s*view\w+", views_block))
        assert links == views, (links, views)

    def test_studies_api_exposes_trial_series(self, cluster):
        """/api/studies/{ns}: the studies view's per-trial objective
        series + best-trial rollup, straight from the StudyJob status
        the controller maintains."""
        cluster.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "StudyJob",
            "metadata": {"name": "tune-lr", "namespace": "kubeflow"},
            "spec": {"studyName": "tune-lr", "optimizationtype": "minimize",
                     "objectivevaluename": "loss"},
            "status": {
                "conditions": [{"type": "Running", "status": "True"}],
                "trialsTotal": 3, "trialsSucceeded": 2, "trialsFailed": 0,
                "bestTrial": {"name": "t-1", "objective": 0.41,
                              "parameters": {"lr": 0.01}},
                "trials": [
                    {"name": "t-0", "status": "Succeeded",
                     "objective": 0.52, "parameters": {"lr": 0.1}},
                    {"name": "t-1", "status": "Succeeded",
                     "objective": 0.41, "parameters": {"lr": 0.01}},
                    {"name": "t-2", "status": "Running",
                     "parameters": {"lr": 0.001}},
                ]},
        })
        server = DashboardServer(cluster)
        port = server.start()
        try:
            studies = get_json(
                f"http://127.0.0.1:{port}/api/studies/kubeflow")
            assert len(studies) == 1
            s = studies[0]
            assert s["phase"] == "Running"
            assert s["optimization"] == "minimize"
            assert s["bestTrial"]["objective"] == 0.41
            assert [t["objective"] for t in s["trials"]] == [0.52, 0.41,
                                                             None]
            # a namespace with no StudyJob CRD installed returns []
            assert get_json(
                f"http://127.0.0.1:{port}/api/studies/alice") == []
        finally:
            server.stop()

    @pytest.mark.katib
    def test_experiments_api_rollup_and_trial_table(self, cluster):
        """/api/katib/experiments: fleet rollup with search economics;
        the detail route exposes the full trial table (phase, objective,
        chips, start kind, stopped-early)."""
        cluster.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "Experiment",
            "metadata": {"name": "sweep", "namespace": "kubeflow"},
            "spec": {
                "objective": {"type": "maximize", "metric": "accuracy"},
                "algorithm": {"name": "grid"},
            },
            "status": {
                "conditions": [{"type": "Running", "status": "True"}],
                "trialsTotal": 3, "trialsRunning": 1,
                "trialsSucceeded": 1, "trialsFailed": 0,
                "trialsStopped": 1,
                "bestTrial": {"name": "sweep-t1", "objective": 0.93,
                              "parameters": {"--lr": 0.1}},
                "trialsPerHour": 12.5,
                "chipHours": {"total": 4.0, "goodput": 3.6,
                              "badput": 0.4, "saved": 1.2},
                "warmStartFraction": 1.0,
                "trials": [
                    {"name": "sweep-t0", "status": "Stopped",
                     "objective": 0.4, "parameters": {"--lr": 0.01},
                     "chips": 8, "startKind": "cold",
                     "stoppedEarly": True, "generation": 0},
                    {"name": "sweep-t1", "status": "Succeeded",
                     "objective": 0.93, "parameters": {"--lr": 0.1},
                     "chips": 8, "startKind": "aot",
                     "stoppedEarly": False, "generation": 0},
                    {"name": "sweep-t2", "status": "Running",
                     "parameters": {"--lr": 0.5}, "chips": 8,
                     "startKind": "warm", "stoppedEarly": False,
                     "generation": 0},
                ]},
        })
        # the admission shorthand (algorithm as a plain name) must not
        # 500 the list view — it regressed once
        cluster.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "Experiment",
            "metadata": {"name": "shorthand", "namespace": "kubeflow"},
            "spec": {"algorithm": "random"},
        })
        server = DashboardServer(cluster)
        port = server.start()
        try:
            exps = get_json(
                f"http://127.0.0.1:{port}/api/katib/experiments")
            assert len(exps) == 2
            assert {x["name"]: x["algorithm"] for x in exps} == \
                {"shorthand": "random", "sweep": "grid"}
            e = next(x for x in exps if x["name"] == "sweep")
            assert e["phase"] == "Running"
            assert e["algorithm"] == "grid"
            assert e["trialsPerHour"] == 12.5
            assert e["warmStartFraction"] == 1.0
            assert e["chipHours"]["saved"] == 1.2
            assert "trials" not in e  # the list view stays light
            detail = get_json(f"http://127.0.0.1:{port}"
                              f"/api/katib/experiments/kubeflow/sweep")
            assert [t["startKind"] for t in detail["trials"]] == \
                ["cold", "aot", "warm"]
            assert [t["stoppedEarly"] for t in detail["trials"]] == \
                [True, False, False]
            assert all(t["chips"] == 8 for t in detail["trials"])
            # unknown experiment 404s instead of 500ing
            import urllib.error
            with pytest.raises(urllib.error.HTTPError) as err:
                get_json(f"http://127.0.0.1:{port}"
                         f"/api/katib/experiments/kubeflow/nope")
            assert err.value.code == 404
        finally:
            server.stop()

    def test_activities_sorted_newest_first(self, cluster):
        for i, ts in enumerate(["2026-01-01", "2026-03-01", "2026-02-01"]):
            cluster.create({
                "apiVersion": "v1", "kind": "Event",
                "metadata": {"name": f"ev{i}", "namespace": "alice"},
                "reason": f"R{i}", "message": "m", "type": "Normal",
                "lastTimestamp": ts,
                "involvedObject": {"name": "nb"}})
        app = build_dashboard_app(cluster)
        status, events = app.dispatch("GET", "/api/activities/alice", None)
        assert status == 200
        assert [e["reason"] for e in events] == ["R1", "R2", "R0"]

    def test_metrics_pluggable_backend(self, cluster):
        class Fake(MetricsService):
            def query(self, metric_type, window_s):
                return [{"metric": metric_type, "window": window_s}]

        app = build_dashboard_app(cluster, metrics=Fake())
        status, data = app.dispatch("GET", "/api/metrics/podcpu?window=300",
                                    None)
        assert status == 200
        assert data == [{"metric": "podcpu", "window": 300}]
        status, err = app.dispatch("GET", "/api/metrics/gpu", None)
        assert status == 400

    def test_node_metric_counts_pods(self, cluster):
        cluster.create({"apiVersion": "v1", "kind": "Pod",
                        "metadata": {"name": "p", "namespace": "alice"},
                        "spec": {"nodeName": "cpu-0", "containers": []}})
        app = build_dashboard_app(cluster)
        status, data = app.dispatch("GET", "/api/metrics/node", None)
        assert status == 200
        by_node = {d["node"]: d["value"] for d in data}
        assert by_node["cpu-0"] == 1


class TestJupyterWebApp:
    def test_notebook_crud_over_http(self, cluster):
        mgr = build_manager(cluster)
        server = JupyterWebApp(cluster)
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            cfg = get_json(f"{base}/api/config")
            assert cfg["tpuShapes"][1] == "1x1 (1 chip)"

            created = post_json(f"{base}/api/namespaces/alice/notebooks", {
                "name": "research", "image": cfg["images"][1],
                "cpu": "2", "memory": "8Gi", "tpu": "2x2 (4 chips)",
                "workspaceVolume": {"size": "20Gi"},
            })
            assert created["notebook"]["tpu"] == 4

            # workspace PVC was created alongside
            pvcs = get_json(f"{base}/api/namespaces/alice/pvcs")["pvcs"]
            assert pvcs[0]["name"] == "workspace-research"
            assert pvcs[0]["size"] == "20Gi"

            # the controller picks the CR up and it becomes Ready
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            listed = get_json(
                f"{base}/api/namespaces/alice/notebooks")["notebooks"]
            assert listed[0]["status"] == "Running"

            post_json(f"{base}/api/namespaces/alice/notebooks/research",
                      {}, method="DELETE")
            assert get_json(
                f"{base}/api/namespaces/alice/notebooks")["notebooks"] == []
            # cascade removed the statefulset too
            assert cluster.get_or_none("apps/v1", "StatefulSet", "alice",
                                       "research") is None
        finally:
            server.stop()

    def test_duplicate_notebook_409(self, cluster):
        server = JupyterWebApp(cluster)
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            post_json(f"{base}/api/namespaces/alice/notebooks",
                      {"name": "nb1"})
            with pytest.raises(urllib.error.HTTPError) as e:
                post_json(f"{base}/api/namespaces/alice/notebooks",
                          {"name": "nb1"})
            assert e.value.code == 409
        finally:
            server.stop()

    def test_manifest_builder_validation(self):
        with pytest.raises(ApiError, match="name is required"):
            build_notebook_manifest("alice", {})
        with pytest.raises(ApiError, match="unknown TPU shape"):
            build_notebook_manifest("alice", {"name": "x",
                                              "tpu": "8x8 (64 chips)"})
        m = build_notebook_manifest("alice", {
            "name": "x", "dataVolumes": [{"name": "ds1", "path": "/ds"}]})
        spec = m["spec"]["template"]["spec"]
        assert spec["volumes"][0]["persistentVolumeClaim"][
            "claimName"] == "ds1"
        assert spec["containers"][0]["volumeMounts"][0]["mountPath"] == "/ds"

    def test_snapshot_skin_uri_annotation(self, monkeypatch, cluster):
        # the rok-skin analog: a gs:// workspace seed lands as an
        # annotation; other schemes are rejected; the skin rides config
        m = build_notebook_manifest("alice", {
            "name": "x", "snapshotUri": "gs://bucket/snap-1"})
        assert m["metadata"]["annotations"][
            "kubeflow-tpu.org/workspace-snapshot"] == "gs://bucket/snap-1"
        with pytest.raises(ApiError, match="snapshotUri"):
            build_notebook_manifest("alice", {
                "name": "x", "snapshotUri": "rok://old-style"})
        monkeypatch.setenv("KFTPU_JUPYTER_SKIN", "snapshot")
        from kubeflow_tpu.webapps.jupyter import build_jupyter_app
        app = build_jupyter_app(cluster)
        status, cfg = app.dispatch("GET", "/api/config", None)
        assert status == 200 and cfg["skin"] == "snapshot"

    def test_unknown_route_404(self, cluster):
        app = build_dashboard_app(cluster)
        status, err = app.dispatch("GET", "/api/nope", None)
        assert status == 404


class TestRunsPanel:
    def test_runs_api_lists_workflows_and_jobs(self, cluster):
        from kubeflow_tpu.controllers.runtime import Manager
        from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
        from kubeflow_tpu.workflows.engine import WorkflowReconciler
        from kubeflow_tpu.pipelines import Pipeline
        mgr = Manager(cluster)
        mgr.add(WorkflowReconciler())
        mgr.add(TrainingJobReconciler("TPUJob"))
        p = Pipeline("pipe")
        p.container("a", image="busybox", command=["true"])
        p.submit(cluster)
        cluster.create({
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "train", "namespace": "kubeflow"},
            "spec": {"replicaSpecs": {"TPU": {
                "tpuTopology": "v5e-8",
                "template": {"spec": {"containers": [
                    {"name": "w", "image": "x"}]}}}}},
        })
        study = {
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "StudyJob",
            "metadata": {"name": "sweep", "namespace": "kubeflow"},
            "spec": {},
            "status": {"trialsTotal": 3, "trialsSucceeded": 2,
                       "bestTrial": {"name": "t-1", "objective": 0.91}},
        }
        cluster.create(study)
        for _ in range(4):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
        server = DashboardServer(cluster)
        port = server.start()
        try:
            runs = get_json(f"http://127.0.0.1:{port}/api/runs/kubeflow")
            by_name = {(r["kind"], r["name"]): r for r in runs}
            assert ("Workflow", "pipe") in by_name
            assert ("TPUJob", "train") in by_name
            assert ("StudyJob", "sweep") in by_name
            assert by_name[("StudyJob", "sweep")]["progress"] == \
                "2/3 trials, best 0.91"
            assert by_name[("TPUJob", "train")]["phase"] in (
                "Created", "Running")
            # the SPA bundle exposes the view and the sidebar links it
            import urllib.request
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/app.js", timeout=10) as r:
                js = r.read().decode()
            assert "viewRuns" in js and "api/runs/" in js
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/", timeout=10) as r:
                html = r.read().decode()
            assert 'data-view="runs"' in html
        finally:
            server.stop()
