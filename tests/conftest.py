"""Test config: force an 8-device virtual CPU mesh BEFORE jax backends init.

All tests run on CPU with 8 virtual devices so multi-chip sharding
(dp/tp/pp/sp/ep) is exercised without TPU hardware — the build-plan's
"fake slice backend" tier (SURVEY.md §4).

Note: the axon site hook imports jax at interpreter startup, so env vars
alone are too late; jax backends are still uninitialized at conftest import,
so jax.config.update redirects them.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (already in sys.modules via the axon site hook)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_platform_name", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _clear_fused_routing_env(monkeypatch):
    """Shield every test from ambient fused-routing env (the TPU
    measurement session exports KFTPU_FUSED_DISABLE_SPATIAL and a
    routing table; tests that WANT them set them via monkeypatch, which
    runs after this autouse delenv)."""
    monkeypatch.delenv("KFTPU_FUSED_DISABLE_SPATIAL", raising=False)
    monkeypatch.delenv("KFTPU_FUSED_ROUTING_TABLE", raising=False)
