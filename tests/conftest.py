"""Test config: force an 8-device virtual CPU mesh BEFORE jax is imported.

All tests run on CPU with 8 virtual devices so multi-chip sharding
(dp/tp/pp/sp/ep) is exercised without TPU hardware — the build-plan's
"fake slice backend" tier (SURVEY.md §4).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
