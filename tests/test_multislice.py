"""Multi-slice DCN training (ISSUE 15): rung 1 (DCN-aware mesh/rules —
no involuntary full reshard) and rung 2 (MPMD pipeline-over-DCN — one
program per slice, explicit transfers, 1F1B schedule, pipeline_bubble
goodput category)."""

import json

import pytest

from kubeflow_tpu.api.trainingjob import (DCN_LEGAL_AXES, MultisliceSpec,
                                          ShardingSpec, TrainingJob,
                                          dcn_crossing_axes)

pytestmark = pytest.mark.multislice


def _tpu_manifest(num_slices=2, sharding=None, multislice=None,
                  topology="v5e-4"):
    spec = {"replicaSpecs": {"TPU": {
        "tpuTopology": topology, "numSlices": num_slices,
        "template": {"spec": {"containers": [{"name": "c"}]}}}}}
    if sharding is not None:
        spec["sharding"] = sharding
    if multislice is not None:
        spec["multislice"] = multislice
    return {"apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "ms", "namespace": "ns"},
            "spec": spec}


class TestDcnCrossingAxes:
    """The jax-free DCN-major arithmetic admission rejects on."""

    def test_single_slice_never_crosses(self):
        assert dcn_crossing_axes({"data": 2, "tensor": 4}, 1) == ()

    def test_data_major_axis_crosses(self):
        # DCN-major order: the outermost nontrivial axis spans slices
        assert dcn_crossing_axes(
            {"data": 2, "fsdp": 2, "tensor": 2}, 2) == ("data",)

    def test_inner_axes_stay_intra_slice(self):
        crossing = dcn_crossing_axes(
            {"data": 2, "fsdp": 2, "tensor": 2}, 2)
        assert "tensor" not in crossing and "fsdp" not in crossing

    def test_tensor_spanning_slices_crosses(self):
        assert dcn_crossing_axes({"tensor": 8}, 2) == ("tensor",)

    def test_fsdp_can_legally_cross(self):
        # with data=1, fsdp is the outermost nontrivial axis — it spans
        # slices, and it is a DCN_LEGAL axis (gradient traffic)
        assert dcn_crossing_axes({"fsdp": 4, "tensor": 2}, 2) == \
            ("fsdp",)
        assert "fsdp" in DCN_LEGAL_AXES

    def test_matches_brute_force(self):
        # exactness drill: compare against direct position enumeration
        axes = ("data", "fsdp", "expert", "pipeline", "sequence",
                "tensor")
        cases = [
            ({"data": 2, "fsdp": 2, "tensor": 2}, 2),
            ({"data": 4, "tensor": 2}, 4),
            ({"fsdp": 2, "sequence": 2, "tensor": 2}, 2),
            ({"data": 2, "pipeline": 2, "tensor": 2}, 4),
            ({"expert": 2, "tensor": 4}, 2),
        ]
        for sizes, n_slices in cases:
            total = 1
            for a in axes:
                total *= sizes.get(a, 1)
            cps = total // n_slices
            strides = {}
            inner = 1
            for a in reversed(axes):
                strides[a] = inner
                inner *= sizes.get(a, 1)
            expect = []
            for a in axes:
                size = sizes.get(a, 1)
                if size <= 1:
                    continue
                hit = False
                for p in range(total):
                    coord = (p // strides[a]) % size
                    for c in range(size):
                        q = p + (c - coord) * strides[a]
                        if q // cps != p // cps:
                            hit = True
                            break
                    if hit:
                        break
                if hit:
                    expect.append(a)
            assert dcn_crossing_axes(sizes, n_slices, axes=axes) == \
                tuple(expect), (sizes, n_slices)

    def test_indivisible_raises(self):
        with pytest.raises(ValueError, match="divisible"):
            dcn_crossing_axes({"data": 3}, 2)


class TestAdmission:
    """DCN-layout rejection happens at apply, not at compile."""

    def test_legal_multislice_sharding_admits(self):
        job = TrainingJob.from_manifest(_tpu_manifest(
            sharding={"data": 2, "fsdp": 2, "tensor": 2}))
        assert job.tpu_spec.num_slices == 2

    def test_cross_dcn_tensor_layout_rejected(self):
        with pytest.raises(ValueError, match="cross the DCN"):
            TrainingJob.from_manifest(_tpu_manifest(
                sharding={"data": 1, "tensor": 8}))

    def test_cross_dcn_sequence_layout_rejected(self):
        with pytest.raises(ValueError, match="cross the DCN"):
            TrainingJob.from_manifest(_tpu_manifest(
                sharding={"data": 1, "sequence": 8}))

    def test_single_slice_tensor_everything_admits(self):
        job = TrainingJob.from_manifest(_tpu_manifest(
            num_slices=1, topology="v5e-8",
            sharding={"data": 1, "tensor": 8}))
        assert job.tpu_spec.num_slices == 1

    def test_pipeline_axis_may_cross(self):
        # pipeline over DCN is deliberate stage traffic, not rejected
        job = TrainingJob.from_manifest(_tpu_manifest(
            sharding={"data": 1, "pipeline": 2, "tensor": 4}))
        assert job.tpu_spec.num_slices == 2

    def test_multislice_pipeline_needs_two_slices(self):
        with pytest.raises(ValueError, match="numSlices >= 2"):
            TrainingJob.from_manifest(_tpu_manifest(
                num_slices=1, topology="v5e-8",
                multislice={"pipeline": True}))


class TestMultisliceSpec:
    def test_round_trip_and_env(self):
        spec = MultisliceSpec.from_dict({"pipeline": True,
                                         "microbatches": 8})
        assert spec.pipeline_enabled
        assert spec.to_dict() == {"pipeline": True, "microbatches": 8}
        assert spec.to_env() == {"KFTPU_MULTISLICE_PIPELINE": "1",
                                 "KFTPU_MULTISLICE_MICROBATCHES": "8"}
        job = TrainingJob.from_manifest(_tpu_manifest(
            multislice={"pipeline": True, "microbatches": 8}))
        assert job.multislice == spec
        assert job.to_manifest()["spec"]["multislice"] == spec.to_dict()

    def test_absent_block_is_default_off(self):
        job = TrainingJob.from_manifest(_tpu_manifest())
        assert not job.multislice.pipeline_enabled
        assert job.multislice.to_env() == {}
        assert "multislice" not in job.to_manifest()["spec"]

    def test_admission_rejects_garbage(self):
        with pytest.raises(ValueError, match="unknown"):
            MultisliceSpec.from_dict({"pipelines": True})
        with pytest.raises(ValueError, match="microbatches"):
            MultisliceSpec.from_dict({"microbatches": 0})
        with pytest.raises(ValueError, match="boolean"):
            MultisliceSpec.from_dict({"pipeline": "yes"})
        with pytest.raises(ValueError, match="mapping"):
            MultisliceSpec.from_dict([True])
        # microbatches without the pipeline is a silent no-op — reject
        with pytest.raises(ValueError, match="requires"):
            MultisliceSpec.from_dict({"microbatches": 8})


class TestDcnAwareRules:
    def test_transformer_rules_declare_vocab_table_unsafe(self):
        from kubeflow_tpu.parallel.sharding_rules import \
            TRANSFORMER_RULES
        assert "vocab_table" in TRANSFORMER_RULES.dcn_unsafe
        # single-slice resolution is IDENTICAL (the same object)
        assert TRANSFORMER_RULES.dcn_aware(1) is TRANSFORMER_RULES

    def test_dcn_aware_replicates_unsafe_axes(self):
        from kubeflow_tpu.parallel.mesh import build_mesh
        from kubeflow_tpu.parallel.sharding_rules import \
            TRANSFORMER_RULES
        mesh = build_mesh(ShardingSpec(data=2, fsdp=2, tensor=2))
        rules2 = TRANSFORMER_RULES.dcn_aware(2)
        assert rules2 is not TRANSFORMER_RULES
        # the gather-indexed table dim replicates...
        assert rules2.spec_for(("vocab_table", "embed"), mesh) == \
            rules2.spec_for((None, "embed"), mesh)
        # ...but the head's matmul vocab stays tensor-sharded
        base = TRANSFORMER_RULES.spec_for(("embed", "vocab"), mesh)
        assert rules2.spec_for(("embed", "vocab"), mesh) == base

    def test_builder_applies_dcn_aware_only_multislice(self):
        import optax

        from kubeflow_tpu.parallel.mesh import build_mesh
        from kubeflow_tpu.parallel.sharding_rules import \
            TRANSFORMER_RULES
        from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

        mesh = build_mesh(ShardingSpec(data=2, fsdp=2, tensor=2))

        def mk(**kw):
            return TrainStepBuilder(
                mesh=mesh, loss_fn=lambda *a: None,
                optimizer=optax.sgd(1e-2), rules=TRANSFORMER_RULES,
                param_logical_axes={}, **kw)

        assert mk(num_slices=1).rules is TRANSFORMER_RULES
        assert mk(num_slices=2).rules is not TRANSFORMER_RULES
        assert mk(num_slices=2, dcn_aware=False).rules is \
            TRANSFORMER_RULES


class TestMeshInvariants:
    """mesh_from_contract DCN-major invariants (the satellite drill)."""

    def test_data_axis_spans_slices(self):
        import jax

        from kubeflow_tpu.api.topology import (TopologyContract,
                                               parse_topology)
        from kubeflow_tpu.parallel.mesh import mesh_from_contract
        contract = TopologyContract(
            coordinator_address="t:1", num_processes=2, process_id=0,
            slice_topology=parse_topology("v5e-4"), num_slices=2,
            slice_id=0)
        mesh = mesh_from_contract(contract,
                                  ShardingSpec(data=2, fsdp=2, tensor=2))
        devices = jax.devices()
        # row 0 of the data axis is exactly slice 0's devices
        assert {d.id for d in mesh.devices[0].flatten()} == \
            {d.id for d in devices[:4]}
        from kubeflow_tpu.parallel.mesh import slice_crossing_axes
        crossing = slice_crossing_axes(mesh, 2)
        assert "data" in crossing
        assert "tensor" not in crossing and "sequence" not in crossing

    def test_num_slices_of_defaults_single(self):
        from kubeflow_tpu.parallel.mesh import build_mesh, num_slices_of
        assert num_slices_of(build_mesh(ShardingSpec(data=8))) == 1


class TestScheduleModel:
    """1F1B order + list-schedule bubble model (pure host math)."""

    def test_stage_op_order_covers_all_ops(self):
        from kubeflow_tpu.parallel.multislice import (BWD, FWD, FWDBWD,
                                                      stage_op_order)
        S, M = 4, 8
        for s in range(S):
            ops = stage_op_order(s, S, M)
            if s == S - 1:
                assert ops == [(FWDBWD, m) for m in range(M)]
            else:
                assert sorted(o for o in ops if o[0] == FWD) == \
                    [(FWD, m) for m in range(M)]
                assert sorted(o for o in ops if o[0] == BWD) == \
                    [(BWD, m) for m in range(M)]
                # a microbatch's backward never precedes its forward
                for m in range(M):
                    assert ops.index((FWD, m)) < ops.index((BWD, m))

    def test_balanced_durations_hit_near_ideal_bubble(self):
        from kubeflow_tpu.parallel.multislice import (BWD, FWD, FWDBWD,
                                                      model_schedule,
                                                      stage_op_order)
        S, M = 2, 8
        durations = {}
        for s in range(S):
            for kind, m in stage_op_order(s, S, M):
                # forward 1 unit, backward 2, fused 3 — balanced stages
                durations[(kind, s, m)] = \
                    {FWD: 1.0, BWD: 2.0, FWDBWD: 3.0}[kind]
        rep = model_schedule(durations, S, M)
        assert rep.makespan_s > 0
        ideal = (S - 1) / (M + S - 1)
        # balanced stages land near the analytic GPipe bound
        assert rep.bubble_fraction == pytest.approx(ideal, abs=0.08)
        assert rep.to_dict()["idealBubbleFraction"] == \
            pytest.approx(ideal, abs=1e-6)

    def test_single_stage_has_no_bubble(self):
        from kubeflow_tpu.parallel.multislice import (FWDBWD,
                                                      model_schedule)
        durations = {(FWDBWD, 0, m): 1.0 for m in range(4)}
        rep = model_schedule(durations, 1, 4)
        assert rep.bubble_fraction == 0.0
        assert rep.makespan_s == pytest.approx(4.0)

    def test_partition_and_groups(self):
        import jax
        import numpy as np

        from kubeflow_tpu.parallel.multislice import (
            partition_stacked, slice_device_groups, stage_meshes)
        groups = slice_device_groups(jax.devices(), 2)
        assert [len(g) for g in groups] == [4, 4]
        meshes = stage_meshes(jax.devices(), 4)
        assert len(meshes) == 4
        assert all(int(m.shape["data"]) == 2 for m in meshes)
        with pytest.raises(ValueError, match="split"):
            slice_device_groups(jax.devices(), 3)
        chunks = partition_stacked({"w": np.arange(8).reshape(8, 1)}, 2)
        assert chunks[0]["w"].tolist() == [[0], [1], [2], [3]]
        assert chunks[1]["w"].tolist() == [[4], [5], [6], [7]]
        with pytest.raises(ValueError, match="divisible"):
            partition_stacked({"w": np.arange(6).reshape(6, 1)}, 4)


@pytest.mark.compute
class TestEngine:
    """The MPMD engine end-to-end on emulated slices (8 CPU devices)."""

    def _cfg(self, layers=2):
        import jax.numpy as jnp

        from kubeflow_tpu.models import transformer as T
        return T.TransformerConfig(
            vocab_size=64, num_layers=layers, embed_dim=32, num_heads=2,
            head_dim=16, mlp_dim=64, max_seq_len=16, dtype=jnp.float32)

    def _engine(self, cfg, num_slices=2, micro=4, devices=None):
        import jax
        import optax

        from kubeflow_tpu.models.transformer import multislice_stage_fns
        from kubeflow_tpu.parallel.multislice import (MPMDPipeline,
                                                      stage_meshes)
        init_fn, embed_fn, block_fn, head_loss_fn = \
            multislice_stage_fns(cfg)
        engine = MPMDPipeline(
            meshes=stage_meshes(devices or jax.devices(), num_slices),
            embed_fn=embed_fn, block_fn=block_fn,
            head_loss_fn=head_loss_fn, optimizer=optax.adamw(1e-3),
            num_microbatches=micro, grad_clip_norm=1.0)
        return engine, init_fn

    def test_parity_vs_single_program(self):
        import jax
        import optax

        from kubeflow_tpu.models import transformer as T
        from kubeflow_tpu.parallel.mesh import build_mesh
        from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

        cfg = self._cfg()
        spec = T.pipelined_workload_spec(cfg=cfg, seq_len=16, mesh=None)
        ref = TrainStepBuilder(
            mesh=build_mesh(ShardingSpec(data=8)),
            loss_fn=spec.loss_fn,
            optimizer=optax.chain(optax.clip_by_global_norm(1.0),
                                  optax.adamw(1e-3)))
        state_r = ref.init(spec.init_fn, jax.random.PRNGKey(0))
        step_r = ref.build()

        engine, init_fn = self._engine(cfg)
        state_m = engine.init(lambda r: init_fn(r, 16),
                              jax.random.PRNGKey(0))

        batches = [spec.batch_fn(jax.random.PRNGKey(7 + i), 16)
                   for i in range(2)]
        for b in batches:
            state_r, mr = step_r(state_r, ref.place_batch(b))
            state_m, mm = engine.step(state_m, engine.place_batch(b))
            assert abs(float(mr["loss"]) - mm["loss"]) <= 1e-5
        assert int(state_m.step) == 2

    def test_report_counts_explicit_transfers(self):
        import jax
        engine, init_fn = self._engine(self._cfg(), micro=4)
        state = engine.init(lambda r: init_fn(r, 16),
                            jax.random.PRNGKey(0))
        tokens = {"tokens": jax.numpy.zeros((16, 16), jax.numpy.int32)}
        engine.step(state, engine.place_batch(tokens))
        rep = engine.last_report
        # (S-1)*M activations fwd + M targets + (S-1)*M cotangents
        assert rep.dcn_transfers == 4 + 4 + 4
        assert rep.dcn_bytes > 0
        assert 0.0 <= rep.bubble_fraction < 1.0
        d = rep.to_dict()
        assert d["numStages"] == 2 and d["numMicrobatches"] == 4
        json.dumps(d)   # span/bench payload must be JSON-clean

    def test_microbatch_divisibility_rejected(self):
        import jax
        engine, init_fn = self._engine(self._cfg(), micro=5)
        state = engine.init(lambda r: init_fn(r, 16),
                            jax.random.PRNGKey(0))
        tokens = {"tokens": jax.numpy.zeros((16, 16), jax.numpy.int32)}
        with pytest.raises(ValueError, match="divisible"):
            engine.step(state, engine.place_batch(tokens))

    def test_stage_programs_carry_no_cross_slice_collectives(self):
        """The MPMD promise: per-stage programs have NO compiler-
        inserted cross-slice traffic — every DCN byte is an explicit
        transfer the schedule counts."""
        import jax

        from kubeflow_tpu.obs.collectives import parse_hlo_collectives
        from kubeflow_tpu.parallel.multislice import FWD
        engine, init_fn = self._engine(self._cfg())
        state = engine.init(lambda r: init_fn(r, 16),
                            jax.random.PRNGKey(0))
        tok0 = jax.ShapeDtypeStruct((4, 16), jax.numpy.int32)
        hlo = engine.stage_hlo(FWD, 0, state.params[0], tok0)
        for op in parse_hlo_collectives(hlo):
            groups = op.groups or []
            for g in groups:
                # stage 0's mesh is its own 4 devices: participant ids
                # beyond them would be cross-slice
                assert all(p < 4 for p in g), (op.name, g)

    def test_aot_export_load_round_trip(self, tmp_path):
        import jax

        from kubeflow_tpu.runtime import aot as aot_mod
        cfg = self._cfg()
        engine, init_fn = self._engine(cfg)
        state = engine.init(lambda r: init_fn(r, 16),
                            jax.random.PRNGKey(0))
        tokens = {"tokens": jax.numpy.zeros((16, 16), jax.numpy.int32)}
        batch = engine.place_batch(tokens)

        def key_fn(s, kind):
            return aot_mod.step_key(
                topology="v5e-4", num_slices=2, model_fingerprint="fp",
                weight_update="mpmd", sharding={"data": 4},
                global_batch=16,
                extra={"stage": s, "program": kind})

        keys = engine.export_stages(str(tmp_path), state, batch, key_fn)
        # 2S-1 schedule-facing programs: fwd+bwd per non-last stage,
        # one fused fwd+loss+bwd on the last
        assert len(keys) == 3 and len(set(keys)) == 3
        state1, m1 = engine.step(state, batch)

        # a FRESH engine loads every stage program — no XLA
        engine2, init_fn2 = self._engine(cfg)
        state2 = engine2.init(lambda r: init_fn2(r, 16),
                              jax.random.PRNGKey(0))
        n = engine2.load_stages(str(tmp_path), state2, batch, key_fn)
        assert n == engine2.num_programs == 3
        state2b, m2 = engine2.step(state2, batch)
        assert m2["loss"] == pytest.approx(m1["loss"], abs=1e-6)
        # reset drops the loaded programs (the fallback ladder's rung)
        engine2.reset_programs()
        assert not engine2._programs


@pytest.mark.compute
class TestWorkerIntegration:
    def test_train_multislice_emits_bubble_ledger(self, tmp_path,
                                                  monkeypatch):
        """The worker-integrated path: train(multislice_pipeline=True)
        over 2 emulated slices streams window + pipeline-bubble spans,
        and the goodput ledger carries a nonzero pipeline_bubble
        category that still sums to wall-clock."""
        from kubeflow_tpu.models import transformer as T
        from kubeflow_tpu.obs import goodput as gp
        from kubeflow_tpu.obs.trace import load_spans
        from kubeflow_tpu.runtime.worker import train

        monkeypatch.setenv("KFTPU_NUM_SLICES", "2")
        sink = str(tmp_path / "spans.jsonl")
        result = train(
            workload="transformer-pipelined", steps=4, global_batch=16,
            sync_every=2, span_path=sink, multislice_pipeline=True,
            multislice_microbatches=4, handle_sigterm=False,
            workload_kwargs={"cfg": T.TransformerConfig.tiny()})
        assert result.steps == 4
        ledger = gp.decompose(load_spans(sink))
        assert ledger["badputSeconds"][gp.BADPUT_PIPELINE_BUBBLE] > 0
        assert gp.categories_sum_ok(ledger)
        names = {s.get("name") for s in load_spans(sink)}
        assert gp.SPAN_PIPELINE_BUBBLE in names
        assert "multislice-profile" in names

    def test_train_multislice_rejects_wrong_workload(self, monkeypatch):
        from kubeflow_tpu.runtime.worker import train
        monkeypatch.setenv("KFTPU_NUM_SLICES", "2")
        with pytest.raises(ValueError, match="transformer-pipelined"):
            train(workload="transformer", steps=1,
                  multislice_pipeline=True, handle_sigterm=False)
