"""Communication observability (ISSUE 13): the HLO collective analyzer
(obs/collectives.py) — parser semantics on canned HLO snippets (async
-start forms, iota/literal replica groups, tuple-shaped combined
collectives, degenerate groups), ICI/DCN classification on 1-slice vs
2-slice meshes, the full-reshard detector's positive/negative drill,
the modeled optimizer-update yardstick, worker comm-profile span +
gauge wiring, and the dashboard comm endpoint."""

import json

import pytest

from kubeflow_tpu.obs.collectives import (
    COMM_PROFILE_ENV, COMM_PROFILE_SPAN, LINK_DCN, LINK_ICI, LINK_LOCAL,
    analyze_hlo, collective_counts, detect_full_reshard,
    export_comm_metrics, modeled_update_dcn_bytes, parse_hlo_collectives,
    slice_assignment)
from kubeflow_tpu.obs.trace import SPAN_PATH_ENV, TRACE_ID_ANNOTATION

pytestmark = pytest.mark.comm

ONE_SLICE_8 = [0] * 8
TWO_SLICE_8 = [0, 0, 0, 0, 1, 1, 1, 1]

META_MODEL = ('metadata={op_name="jit(step_fn)/jit(main)/'
              'jvp(TransformerLM)/tok_embed/gather" '
              'source_file="/repo/kubeflow_tpu/models/transformer.py" '
              'source_line=138}')
META_UPDATE = ('metadata={op_name="jit(step_fn)/jit(main)/add" '
               'source_file="/repo/kubeflow_tpu/runtime/trainstep.py" '
               'source_line=228}')
META_PIPELINE = ('metadata={op_name="jit(step_fn)/jit(main)/ppermute" '
                 'source_file="/repo/kubeflow_tpu/parallel/pipeline.py" '
                 'source_line=143}')
META_MULTISLICE = ('metadata={op_name="jit(run)/jit(main)/transfer" '
                   'source_file='
                   '"/repo/kubeflow_tpu/parallel/multislice.py" '
                   'source_line=330}')


def _hlo(*lines) -> str:
    return "\n".join(["HloModule test", "ENTRY %main () -> f32[] {",
                      *(f"  {ln}" for ln in lines), "}"])


class TestParser:
    def test_literal_groups_and_shapes(self):
        hlo = _hlo('%ar = f32[128,8]{1,0} all-reduce(f32[128,8]{1,0} '
                   '%g), channel_id=1, '
                   'replica_groups={{0,1,2,3},{4,5,6,7}}, '
                   'use_global_device_ids=true, to_apply=%sum')
        ops = parse_hlo_collectives(hlo)
        assert len(ops) == 1
        op = ops[0]
        assert op.kind == "all-reduce" and not op.is_async_start
        assert op.payload_bytes == 128 * 8 * 4
        assert op.groups == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def test_iota_groups_expand_with_transpose(self):
        # [2,4]<=[4,2]T(1,0): iota(8).reshape(4,2).T.flatten() —
        # exactly the gradient-reduction groups the 2-slice mixed mesh
        # emits (observed in the MULTICHIP_r05 config's HLO)
        hlo = _hlo('%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
                   'replica_groups=[2,4]<=[4,2]T(1,0), to_apply=%sum')
        assert parse_hlo_collectives(hlo)[0].groups == \
            [[0, 2, 4, 6], [1, 3, 5, 7]]

    def test_iota_groups_without_transpose(self):
        hlo = _hlo('%ag = f32[64]{0} all-gather(f32[8]{0} %x), '
                   'replica_groups=[1,8]<=[8], dimensions={0}')
        assert parse_hlo_collectives(hlo)[0].groups == \
            [[0, 1, 2, 3, 4, 5, 6, 7]]

    def test_async_start_counted_done_ignored(self):
        # XLA:TPU splits collectives into start/done pairs; only the
        # -start op names the groups — counting both would double
        hlo = _hlo(
            '%ars = f32[128]{0} all-reduce-start(f32[128]{0} %g), '
            'replica_groups={{0,1}}, to_apply=%sum',
            '%ard = f32[128]{0} all-reduce-done(f32[128]{0} %ars)')
        ops = parse_hlo_collectives(hlo)
        assert len(ops) == 1
        assert ops[0].is_async_start
        assert ops[0].payload_bytes == 128 * 4

    def test_all_gather_start_tuple_counts_result_half(self):
        # all-gather-start returns (operand, result): the payload is the
        # gathered RESULT, not operand + result
        hlo = _hlo('%ags = (f32[8]{0}, f32[64]{0}) all-gather-start('
                   'f32[8]{0} %x), replica_groups={{0,1,2,3,4,5,6,7}}, '
                   'dimensions={0}')
        assert parse_hlo_collectives(hlo)[0].payload_bytes == 64 * 4

    def test_combined_tuple_collective_sums_elements(self):
        # a combined (tuple-shaped) sync all-reduce reduces every
        # element: payload is the sum
        hlo = _hlo('%ar = (f32[16]{0}, bf16[32]{0}) all-reduce('
                   'f32[16]{0} %a, bf16[32]{0} %b), '
                   'replica_groups={{0,1}}, to_apply=%sum')
        assert parse_hlo_collectives(hlo)[0].payload_bytes == \
            16 * 4 + 32 * 2

    def test_collective_permute_pairs(self):
        hlo = _hlo('%cp = f32[128,32]{1,0} collective-permute('
                   'f32[128,32]{1,0} %x), channel_id=11, '
                   'source_target_pairs={{0,0},{4,2},{1,5}}, '
                   + META_MODEL)
        op = parse_hlo_collectives(hlo)[0]
        assert op.kind == "collective-permute"
        assert op.pairs == [(0, 0), (4, 2), (1, 5)]
        assert op.source_file.endswith("transformer.py")
        assert op.source_line == 138

    def test_fusion_referencing_collective_not_matched(self):
        hlo = _hlo('%f = f32[8]{0} fusion(f32[8]{0} %all-reduce.1), '
                   'kind=kLoop, calls=%fc')
        assert parse_hlo_collectives(hlo) == []

    def test_metadata_without_source_file(self):
        hlo = _hlo('%ag = f32[64]{0} all-gather(f32[8]{0} %x), '
                   'replica_groups=[1,8]<=[8], dimensions={0}, '
                   'metadata={op_name="jit(step)/gather"}')
        op = parse_hlo_collectives(hlo)[0]
        assert op.op_name == "jit(step)/gather"
        assert op.source_file == "" and not op.in_update_region


class TestClassification:
    def test_single_slice_is_ici(self):
        hlo = _hlo('%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
                   'replica_groups=[1,8]<=[8], to_apply=%sum')
        prof = analyze_hlo(hlo, ONE_SLICE_8)
        op = prof.ops[0]
        assert op.link == LINK_ICI and op.slices_spanned == 1
        assert op.dcn_bytes == 0
        # ring all-reduce over n=8: 2 * P * 7/8
        assert op.ici_bytes == pytest.approx(2 * 64 * 4 * 7 / 8)
        assert prof.dcn_bytes_per_step == 0

    def test_two_slice_hierarchical_split(self):
        hlo = _hlo('%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
                   'replica_groups=[1,8]<=[8], to_apply=%sum')
        op = analyze_hlo(hlo, TWO_SLICE_8).ops[0]
        assert op.link == LINK_DCN and op.slices_spanned == 2
        # inter-slice phase at k=2, intra-slice phase at n_local=4
        assert op.dcn_bytes == pytest.approx(2 * 64 * 4 * 1 / 2)
        assert op.ici_bytes == pytest.approx(2 * 64 * 4 * 3 / 4)

    def test_reduce_scatter_full_payload_is_result_times_group(self):
        hlo = _hlo('%rs = f32[8]{0} reduce-scatter(f32[64]{0} %g), '
                   'replica_groups=[1,8]<=[8], dimensions={0}, '
                   'to_apply=%sum')
        op = analyze_hlo(hlo, TWO_SLICE_8).ops[0]
        # pre-scatter input = result x 8; factor 1
        assert op.dcn_bytes == pytest.approx(8 * 4 * 8 * 1 / 2)

    def test_degenerate_single_member_groups_are_local(self):
        hlo = _hlo('%ag = f32[8]{0} all-gather(f32[8]{0} %x), '
                   'replica_groups={{0},{1},{2},{3},{4},{5},{6},{7}}, '
                   'dimensions={0}')
        op = analyze_hlo(hlo, TWO_SLICE_8).ops[0]
        assert op.link == LINK_LOCAL
        assert op.dcn_bytes == 0 and op.ici_bytes == 0

    def test_empty_replica_groups_means_everyone(self):
        hlo = _hlo('%ar = f32[4]{0} all-reduce(f32[4]{0} %g), '
                   'replica_groups={}, to_apply=%sum')
        op = analyze_hlo(hlo, TWO_SLICE_8).ops[0]
        assert op.group_size == 8 and op.link == LINK_DCN

    def test_permute_crossing_fraction(self):
        # 2 real pairs, 1 crossing: half the payload is DCN
        hlo = _hlo('%cp = f32[100]{0} collective-permute(f32[100]{0} '
                   '%x), source_target_pairs={{0,0},{1,2},{3,4}}')
        op = analyze_hlo(hlo, TWO_SLICE_8).ops[0]
        assert op.link == LINK_DCN
        assert op.dcn_bytes == pytest.approx(400 * 0.5)
        assert op.ici_bytes == pytest.approx(400 * 0.5)

    def test_mesh_axes_labeling(self):
        mesh_axes = [("data", 2), ("fsdp", 2), ("tensor", 2)]
        hlo = _hlo('%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
                   'replica_groups=[2,4]<=[4,2]T(1,0), to_apply=%sum')
        op = analyze_hlo(hlo, TWO_SLICE_8, mesh_axes=mesh_axes).ops[0]
        # groups {0,2,4,6}: data+fsdp vary, tensor fixed
        assert op.axes == ("data", "fsdp")

    def test_bandwidth_knobs(self, monkeypatch):
        monkeypatch.setenv("KFTPU_COMM_ICI_GBPS", "10")
        monkeypatch.setenv("KFTPU_COMM_DCN_GBPS", "1")
        hlo = _hlo('%ar = f32[1000]{0} all-reduce(f32[1000]{0} %g), '
                   'replica_groups=[1,8]<=[8], to_apply=%sum')
        prof = analyze_hlo(hlo, TWO_SLICE_8)
        assert prof.modeled_dcn_seconds == \
            pytest.approx(prof.dcn_bytes_per_step / 1e9)
        assert prof.modeled_ici_seconds == \
            pytest.approx(prof.ici_bytes_per_step / 10e9)

    def test_by_link_op_bytes_reconcile_with_totals(self):
        # a DCN-crossing op has BOTH phases: its ICI-phase bytes must
        # land under the ici rows so the per-link gauge sums match the
        # profile totals an operator sees beside them
        hlo = _hlo(
            '%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
            'replica_groups=[1,8]<=[8], to_apply=%sum',
            '%ag = f32[32]{0} all-gather(f32[16]{0} %x), '
            'replica_groups={{0,1},{2,3},{4,5},{6,7}}, dimensions={0}')
        prof = analyze_hlo(hlo, TWO_SLICE_8)
        rows = prof.by_link_op()
        assert sum(r["bytes"] for (link, _), r in rows.items()
                   if link == LINK_DCN) == \
            pytest.approx(prof.dcn_bytes_per_step)
        assert sum(r["bytes"] for (link, _), r in rows.items()
                   if link == LINK_ICI) == \
            pytest.approx(prof.ici_bytes_per_step)
        # counts still bucket each op under ITS link class
        assert rows[(LINK_DCN, "all-reduce")]["count"] == 1
        assert rows[(LINK_ICI, "all-gather")]["count"] == 1
        assert rows[(LINK_ICI, "all-reduce")]["count"] == 0

    def test_permute_out_of_range_pairs_skipped(self):
        # wrong mesh passed: ids beyond the slice map are skipped like
        # the replica-group path, never an IndexError
        hlo = _hlo('%cp = f32[100]{0} collective-permute(f32[100]{0} '
                   '%x), source_target_pairs={{0,1},{7,4}}')
        op = analyze_hlo(hlo, [0, 0, 1, 1]).ops[0]
        assert op.link == LINK_ICI and op.dcn_bytes == 0

    def test_to_dict_shape(self):
        hlo = _hlo('%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
                   'replica_groups=[1,8]<=[8], to_apply=%sum')
        d = analyze_hlo(hlo, TWO_SLICE_8).to_dict()
        assert d["collectivesPerStep"] == {"dcn": 1, "ici": 0,
                                           "local": 0}
        assert "dcn/all-reduce" in d["byLinkOp"]
        assert d["dcnFullReshard"]["flagged"] is False
        assert d["topOps"][0]["kind"] == "all-reduce"


class TestCollectiveCounts:
    def test_scalar_all_reduce_excluded(self):
        hlo = _hlo('%l = f32[] all-reduce(f32[] %loss), '
                   'replica_groups={{0,1}}, to_apply=%sum',
                   '%g = f32[64]{0} all-reduce(f32[64]{0} %grad), '
                   'replica_groups={{0,1}}, to_apply=%sum')
        assert collective_counts(hlo) == {
            "reduce_scatter": 0, "all_gather": 0,
            "all_reduce_nonscalar": 1}

    def test_async_forms_counted_once(self):
        hlo = _hlo(
            '%rs = f32[8]{0} reduce-scatter-start(f32[64]{0} %g), '
            'replica_groups={{0,1}}, dimensions={0}, to_apply=%sum',
            '%rsd = f32[8]{0} reduce-scatter-done(f32[8]{0} %rs)',
            '%ag = (f32[8]{0}, f32[64]{0}) all-gather-start(f32[8]{0} '
            '%p), replica_groups={{0,1}}, dimensions={0}',
            '%agd = f32[64]{0} all-gather-done((f32[8]{0}, f32[64]{0}) '
            '%ag)')
        assert collective_counts(hlo) == {
            "reduce_scatter": 1, "all_gather": 1,
            "all_reduce_nonscalar": 0}

    def test_bench_reexports_the_shared_vocabulary(self):
        import bench
        assert bench.collective_counts is collective_counts


def _reshard_hlo(meta=META_MODEL):
    """A 2-slice module with a DCN-crossing parameter all-gather in the
    model region — the involuntary-remat signature."""
    return _hlo(
        '%ag = f32[256,32]{1,0} all-gather(f32[128,32]{1,0} %p), '
        'replica_groups={{0,4},{2,6},{1,5},{3,7}}, dimensions={0}, '
        'use_global_device_ids=true, ' + meta,
        '%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
        'replica_groups=[1,8]<=[8], to_apply=%sum, ' + META_MODEL)


class TestDetector:
    def test_flags_model_region_dcn_all_gather(self):
        prof = analyze_hlo(_reshard_hlo(), TWO_SLICE_8)
        v = detect_full_reshard(prof)
        assert v.flagged
        assert len(v.ops) == 1 and v.ops[0]["kind"] == "all-gather"
        assert "involuntary" in v.reason

    def test_flags_metadata_less_dcn_reshard(self):
        # no metadata = model region (conservative: an unattributed DCN
        # reshard should flag, not hide)
        hlo = _hlo('%ag = f32[64]{0} all-gather(f32[8]{0} %p), '
                   'replica_groups=[1,8]<=[8], dimensions={0}')
        assert detect_full_reshard(
            analyze_hlo(hlo, TWO_SLICE_8)).flagged

    def test_update_region_gather_is_clean(self):
        # the ZeRO-2 param re-gather crosses DCN by design: never a flag
        hlo = _hlo('%ag = f32[64]{0} all-gather(f32[8]{0} %p), '
                   'replica_groups=[1,8]<=[8], dimensions={0}, '
                   + META_UPDATE)
        assert not detect_full_reshard(
            analyze_hlo(hlo, TWO_SLICE_8)).flagged

    def test_ici_gather_and_dcn_all_reduce_are_clean(self):
        hlo = _hlo(
            '%ag = f32[64]{0} all-gather(f32[32]{0} %x), '
            'replica_groups={{0,1},{2,3},{4,5},{6,7}}, dimensions={0}, '
            + META_MODEL,
            '%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
            'replica_groups=[1,8]<=[8], to_apply=%sum, ' + META_MODEL)
        assert not detect_full_reshard(
            analyze_hlo(hlo, TWO_SLICE_8)).flagged

    def test_single_slice_never_flags(self):
        assert not detect_full_reshard(
            analyze_hlo(_reshard_hlo(), ONE_SLICE_8)).flagged

    def test_crossing_permute_flags(self):
        hlo = _hlo('%cp = f32[100]{0} collective-permute(f32[100]{0} '
                   '%x), source_target_pairs={{0,4},{4,0}}, '
                   + META_MODEL)
        assert detect_full_reshard(
            analyze_hlo(hlo, TWO_SLICE_8)).flagged

    def test_pipeline_phase_permute_is_clean(self):
        """Deliberate stage send/recv (phase=pipeline) must NEVER read
        as an involuntary reshard — the same DCN-crossing permute flags
        when attributed to the model region (both ways, the satellite
        drill)."""
        permute = ('%cp = f32[100]{0} collective-permute(f32[100]{0} '
                   '%x), source_target_pairs={{0,4},{4,0}}, ')
        for meta in (META_PIPELINE, META_MULTISLICE):
            prof = analyze_hlo(_hlo(permute + meta), TWO_SLICE_8)
            assert prof.ops[0].phase == "pipeline"
            assert not detect_full_reshard(prof).flagged, meta
        # control: the identical op with model-region metadata flags
        assert detect_full_reshard(
            analyze_hlo(_hlo(permute + META_MODEL), TWO_SLICE_8)).flagged

    def test_pipeline_phase_labeled_in_by_link_op(self):
        permute = ('%cp = f32[100]{0} collective-permute(f32[100]{0} '
                   '%x), source_target_pairs={{0,4},{4,0}}, ')
        prof = analyze_hlo(
            _hlo(permute + META_PIPELINE,
                 '%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
                 'replica_groups=[1,8]<=[8], to_apply=%sum, '
                 + META_MODEL),
            TWO_SLICE_8)
        rows = prof.by_link_op()
        assert rows[("dcn", "collective-permute")]["phases"] == \
            {"pipeline": 1}
        assert rows[("dcn", "all-reduce")]["phases"] == {"model": 1}
        d = prof.to_dict()
        assert d["byLinkOp"]["dcn/collective-permute"]["phases"] == \
            {"pipeline": 1}
        assert d["topOps"][0]["phase"] in ("pipeline", "model")


class TestUpdateMetric:
    def test_replicated_style_counts_factor_two(self):
        hlo = _hlo('%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
                   'replica_groups=[1,8]<=[8], to_apply=%sum',
                   '%l = f32[] all-reduce(f32[] %loss), '
                   'replica_groups=[1,8]<=[8], to_apply=%sum')
        prof = analyze_hlo(hlo, TWO_SLICE_8)
        u = modeled_update_dcn_bytes(prof, hlo)
        assert u["style"] == "replicated"
        # the scalar loss all-reduce is not optimizer-update traffic
        assert u["bytes"] == pytest.approx(2 * 64 * 4 * 1 / 2)

    def test_sharded_style_counts_param_regather_once(self):
        hlo = _hlo(
            '%rs = f32[8]{0} reduce-scatter(f32[64]{0} %g), '
            'replica_groups=[1,8]<=[8], dimensions={0}, to_apply=%sum, '
            + META_UPDATE,
            '%ag = f32[64]{0} all-gather(f32[8]{0} %u), '
            'replica_groups=[1,8]<=[8], dimensions={0}, ' + META_UPDATE)
        u = modeled_update_dcn_bytes(
            analyze_hlo(hlo, TWO_SLICE_8), hlo)
        assert u["style"] == "sharded"
        assert u["bytes"] == pytest.approx(64 * 4 * 1 / 2)

    def test_split_gather_pair_merged_via_consumer(self):
        # the CPU partitioner's add(all-gather, all-gather) emission:
        # ONE logical param re-gather, counted once — while two
        # same-shape gathers with SEPARATE consumers stay distinct
        pair = _hlo(
            '%ag.1 = f32[64]{0} all-gather(f32[8]{0} %a), '
            'replica_groups=[1,8]<=[8], dimensions={0}, ' + META_UPDATE,
            '%ag.2 = f32[64]{0} all-gather(f32[8]{0} %b), '
            'replica_groups=[1,8]<=[8], dimensions={0}, ' + META_UPDATE,
            '%add.1 = f32[64]{0} add(f32[64]{0} %ag.1, f32[64]{0} '
            '%ag.2)')
        u = modeled_update_dcn_bytes(analyze_hlo(pair, TWO_SLICE_8),
                                     pair)
        assert u["bytes"] == pytest.approx(64 * 4 * 1 / 2)

        separate = _hlo(
            '%ag.1 = f32[64]{0} all-gather(f32[8]{0} %a), '
            'replica_groups=[1,8]<=[8], dimensions={0}, ' + META_UPDATE,
            '%ag.2 = f32[64]{0} all-gather(f32[8]{0} %b), '
            'replica_groups=[1,8]<=[8], dimensions={0}, ' + META_UPDATE,
            '%n.1 = f32[64]{0} negate(f32[64]{0} %ag.1)',
            '%n.2 = f32[64]{0} negate(f32[64]{0} %ag.2)')
        u2 = modeled_update_dcn_bytes(
            analyze_hlo(separate, TWO_SLICE_8), separate)
        assert u2["bytes"] == pytest.approx(2 * 64 * 4 * 1 / 2)

    def test_merge_never_chains_through_a_merged_gather(self):
        # g2 merges into g1 via add; a later consumer sharing g2 with
        # g3 must NOT merge g3 too — g3 is a distinct logical re-gather
        hlo = _hlo(
            '%g1 = f32[64]{0} all-gather(f32[8]{0} %a), '
            'replica_groups=[1,8]<=[8], dimensions={0}, ' + META_UPDATE,
            '%g2 = f32[64]{0} all-gather(f32[8]{0} %b), '
            'replica_groups=[1,8]<=[8], dimensions={0}, ' + META_UPDATE,
            '%g3 = f32[64]{0} all-gather(f32[8]{0} %c), '
            'replica_groups=[1,8]<=[8], dimensions={0}, ' + META_UPDATE,
            '%add.1 = f32[64]{0} add(f32[64]{0} %g1, f32[64]{0} %g2)',
            '%mul.1 = f32[64]{0} multiply(f32[64]{0} %g2, f32[64]{0} '
            '%g3)')
        u = modeled_update_dcn_bytes(analyze_hlo(hlo, TWO_SLICE_8),
                                     hlo)
        # two logical re-gathers survive (g1+g2 merged, g3 distinct)
        assert u["bytes"] == pytest.approx(2 * 64 * 4 * 1 / 2)

    def test_bandwidth_knob_garbage_warns_and_defaults(self, caplog,
                                                       monkeypatch):
        monkeypatch.setenv("KFTPU_COMM_DCN_GBPS", "6,25")
        hlo = _hlo('%ar = f32[1000]{0} all-reduce(f32[1000]{0} %g), '
                   'replica_groups=[1,8]<=[8], to_apply=%sum')
        import logging
        with caplog.at_level(logging.WARNING,
                             logger="kubeflow_tpu.obs.collectives"):
            prof = analyze_hlo(hlo, TWO_SLICE_8)
        assert prof.dcn_gbps == 6.25   # the default, loudly
        assert any("KFTPU_COMM_DCN_GBPS" in r.message
                   for r in caplog.records)

    def test_sharded_strictly_below_replicated_same_params(self):
        rep = _hlo('%ar = f32[64]{0} all-reduce(f32[64]{0} %g), '
                   'replica_groups=[1,8]<=[8], to_apply=%sum')
        sh = _hlo(
            '%rs = f32[8]{0} reduce-scatter(f32[64]{0} %g), '
            'replica_groups=[1,8]<=[8], dimensions={0}, to_apply=%sum, '
            + META_UPDATE,
            '%ag = f32[64]{0} all-gather(f32[8]{0} %u), '
            'replica_groups=[1,8]<=[8], dimensions={0}, ' + META_UPDATE)
        u_rep = modeled_update_dcn_bytes(
            analyze_hlo(rep, TWO_SLICE_8), rep)
        u_sh = modeled_update_dcn_bytes(
            analyze_hlo(sh, TWO_SLICE_8), sh)
        assert u_sh["bytes"] < u_rep["bytes"]
        # ... while TOTAL wire bytes are conserved (RS+AG == AR): the
        # documented reason the yardstick isolates the update phase
        tot_rep = analyze_hlo(rep, TWO_SLICE_8).dcn_bytes_per_step
        tot_sh = analyze_hlo(sh, TWO_SLICE_8).dcn_bytes_per_step
        assert tot_sh == pytest.approx(tot_rep)


class TestExportMetrics:
    def test_series_visible_then_pruned(self):
        from kubeflow_tpu.obs.registry import (default_registry,
                                               reset_default_registry)
        reset_default_registry()
        try:
            prof = analyze_hlo(_reshard_hlo(), TWO_SLICE_8)
            series = export_comm_metrics(prof)
            text = default_registry().render()
            assert 'kftpu_comm_bytes_per_step{link="dcn",' \
                   'op="all-gather"}' in text
            assert 'kftpu_comm_collectives_per_step{link="dcn",' \
                   'op="all-reduce"} 1' in text
            assert "kftpu_comm_dcn_full_reshard 1" in text
            series.prune()
            text = default_registry().render()
            assert 'link="dcn"' not in text
            assert "kftpu_comm_dcn_full_reshard 0" in text
        finally:
            reset_default_registry()


class TestFlightRecorderComm:
    def test_window_records_carry_modeled_comm_split(self):
        from kubeflow_tpu.runtime.metrics import FlightRecorder
        rec = FlightRecorder(windows=4)
        rec.note_step(data_s=0.01, dispatch_s=0.02)
        rec.close_window(1, 1, 0.1)
        base = rec.snapshot()["records"][-1]
        assert "comm_ici_s" not in base    # no profile yet: no field
        rec.set_comm_model(0.002, 0.005)
        rec.note_step(data_s=0.01, dispatch_s=0.02)
        rec.note_step(data_s=0.01, dispatch_s=0.02)
        rec.close_window(3, 2, 0.2)
        win = rec.snapshot()["records"][-1]
        assert win["comm_ici_s"] == pytest.approx(0.004)
        assert win["comm_dcn_s"] == pytest.approx(0.010)
        # its own keyed field: the measured device_wait residual is NOT
        # reduced by the modeled comm seconds (the first_step_s rule)
        assert win["device_wait_s"] == pytest.approx(
            0.2 - 0.06, abs=1e-6)


def _job_manifest(name="comm-job") -> dict:
    return {"apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": "kubeflow",
                         "uid": "uid-77"},
            "spec": {"replicaSpecs": {"TPU": {
                "tpuTopology": "v5e-8",
                "template": {"spec": {"containers": [
                    {"name": "jax", "image": "trainer:v1"}]}}}}}}


class TestDashboardEndpoint:
    def _write_profile_span(self, sink, trace_id):
        prof = analyze_hlo(_reshard_hlo(), TWO_SLICE_8)
        with open(sink, "w") as f:
            f.write(json.dumps({
                "name": COMM_PROFILE_SPAN, "trace_id": trace_id,
                "start": 1.0, "end": 1.0,
                "attrs": {"step": 1,
                          "profile": prof.to_dict()}}) + "\n")

    def test_comm_endpoint_serves_newest_profile(self, tmp_path,
                                                 monkeypatch):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        sink = str(tmp_path / "spans.jsonl")
        self._write_profile_span(sink, "ct1")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        cluster = FakeCluster()
        manifest = _job_manifest()
        manifest["metadata"]["annotations"] = {TRACE_ID_ANNOTATION:
                                               "ct1"}
        cluster.create(manifest)
        app = build_dashboard_app(cluster)
        status, body = app.dispatch(
            "GET", "/api/obs/comm/kubeflow/comm-job", None)
        assert status == 200
        assert body["profile"]["dcnFullReshard"]["flagged"] is True
        assert body["profile"]["dcnBytesPerStep"] > 0

    def test_no_profile_yet_notes(self, tmp_path, monkeypatch):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        monkeypatch.setenv(SPAN_PATH_ENV, str(tmp_path / "e.jsonl"))
        cluster = FakeCluster()
        manifest = _job_manifest()
        manifest["metadata"]["annotations"] = {TRACE_ID_ANNOTATION:
                                               "ct2"}
        cluster.create(manifest)
        app = build_dashboard_app(cluster)
        status, body = app.dispatch(
            "GET", "/api/obs/comm/kubeflow/comm-job", None)
        assert status == 200 and body["profile"] is None
        assert "note" in body

    def test_unknown_job_404(self):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        app = build_dashboard_app(FakeCluster())
        status, _ = app.dispatch(
            "GET", "/api/obs/comm/kubeflow/ghost", None)
        assert status == 404


@pytest.mark.compute
class TestWorkerIntegration:
    def test_aot_run_emits_profile_span_and_prunes_gauges(
            self, tmp_path, monkeypatch):
        """The free path: with AOT the step is a Compiled object, so
        the default auto mode profiles without a second compile. The
        comm-profile span lands on the trace; the kftpu_comm_* series
        are pruned at teardown."""
        from kubeflow_tpu.obs.registry import (default_registry,
                                               reset_default_registry)
        from kubeflow_tpu.obs.trace import load_spans
        from kubeflow_tpu.runtime.worker import train
        reset_default_registry()
        sink = str(tmp_path / "spans.jsonl")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        monkeypatch.setenv("KFTPU_TRACE_ID", "cw1")
        monkeypatch.delenv(COMM_PROFILE_ENV, raising=False)
        try:
            train(workload="transformer", steps=3, global_batch=8,
                  sync_every=2, aot=True,
                  aot_dir=str(tmp_path / "aot"), workload_kwargs={})
            spans = [s for s in load_spans(sink, trace_id="cw1")
                     if s["name"] == COMM_PROFILE_SPAN]
            assert len(spans) == 1
            prof = spans[0]["attrs"]["profile"]
            # single-slice local mesh: everything is ICI, no red flag
            assert prof["dcnBytesPerStep"] == 0
            assert prof["iciBytesPerStep"] > 0
            assert prof["collectivesPerStep"]["ici"] > 0
            assert prof["dcnFullReshard"]["flagged"] is False
            # teardown pruned the per-(link,op) series
            text = default_registry().render()
            assert 'kftpu_comm_bytes_per_step{' not in text
        finally:
            reset_default_registry()

    def test_forced_jit_profile_and_two_slice_classification(
            self, tmp_path, monkeypatch):
        """KFTPU_COMM_PROFILE=1 forces the jit path to produce HLO
        (a cache-hitting second compile), and a 2-slice contract on the
        ctx classifies the gradient all-reduce as DCN."""
        import jax

        from kubeflow_tpu.api.topology import (TopologyContract,
                                               parse_topology)
        from kubeflow_tpu.api.trainingjob import ShardingSpec
        from kubeflow_tpu.obs.trace import load_spans
        from kubeflow_tpu.parallel.mesh import build_mesh
        from kubeflow_tpu.runtime.bootstrap import WorkerContext
        from kubeflow_tpu.runtime.worker import train
        n_dev = len(jax.devices())
        if n_dev % 2:
            pytest.skip("needs an even device count")
        sink = str(tmp_path / "spans.jsonl")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        monkeypatch.setenv("KFTPU_TRACE_ID", "cw2")
        monkeypatch.setenv(COMM_PROFILE_ENV, "1")
        sharding = ShardingSpec(data=n_dev)
        ctx = WorkerContext(
            contract=TopologyContract(
                coordinator_address="t:1", num_processes=1,
                process_id=0,
                slice_topology=parse_topology(f"v5e-{n_dev // 2}"),
                num_slices=2),
            sharding=sharding, mesh=build_mesh(sharding),
            process_id=0, num_processes=1)
        train(workload="transformer", steps=2, global_batch=n_dev * 2,
              sync_every=2, ctx=ctx, workload_kwargs={})
        spans = [s for s in load_spans(sink, trace_id="cw2")
                 if s["name"] == COMM_PROFILE_SPAN]
        assert len(spans) == 1
        prof = spans[0]["attrs"]["profile"]
        # pure-DP gradients cross the modeled DCN boundary
        assert prof["numSlices"] == 2
        assert prof["dcnBytesPerStep"] > 0
        assert prof["dcnFullReshard"]["flagged"] is False

    def test_disabled_emits_nothing(self, tmp_path, monkeypatch):
        from kubeflow_tpu.obs.trace import load_spans
        from kubeflow_tpu.runtime.worker import train
        sink = str(tmp_path / "spans.jsonl")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        monkeypatch.setenv("KFTPU_TRACE_ID", "cw3")
        monkeypatch.setenv(COMM_PROFILE_ENV, "0")
        train(workload="transformer", steps=2, global_batch=8,
              sync_every=2, aot=True, aot_dir=str(tmp_path / "aot"),
              workload_kwargs={})
        assert not [s for s in load_spans(sink, trace_id="cw3")
                    if s["name"] == COMM_PROFILE_SPAN]


def test_slice_assignment_orders_by_device_assignment():
    import jax

    from kubeflow_tpu.api.trainingjob import ShardingSpec
    from kubeflow_tpu.parallel.mesh import build_mesh
    n = len(jax.devices())
    mesh = build_mesh(ShardingSpec(data=n))
    two = slice_assignment(mesh, 2)
    assert two == [0] * (n // 2) + [1] * (n - n // 2)
    assert slice_assignment(mesh, 1) == [0] * n
