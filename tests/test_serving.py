"""Serving data-plane tests: servable buckets, micro-batcher, REST API,
batch predict — the test_tf_serving.py analog (reference
testing/test_tf_serving.py:60-124 deploys, probes, posts a predict and
asserts on the response; here the server runs in-process)."""

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.serving import (MicroBatcher, ModelRepository, ModelServer,
                                  Servable)
from kubeflow_tpu.serving.batch_predict import run_batch_predict
from kubeflow_tpu.serving.servable import next_bucket, register_model

pytestmark = pytest.mark.compute  # JAX trace/compile tests: excluded from smoke tier


@register_model("double")
def _build_double(dim: int = 4):
    def init_params():
        return {"w": jnp.full((dim,), 2.0)}

    def predict(params, x):
        return {"y": x * params["w"]}

    sig = {"inputs": {"shape": [-1, dim], "dtype": "float32"}}
    return predict, init_params, sig


def _servable(**kw) -> Servable:
    repo = ModelRepository()
    return repo.load("double", "double", **kw)


def test_next_bucket():
    assert next_bucket(1, 64) == 1
    assert next_bucket(3, 64) == 4
    assert next_bucket(64, 64) == 64
    assert next_bucket(100, 64) == 64


def test_servable_padding_and_split():
    s = _servable()
    s.max_batch = 8
    x = np.arange(12 * 4, dtype=np.float32).reshape(12, 4)
    out = s.predict(x)  # 12 > max_batch → split into 8 + 4
    np.testing.assert_allclose(out["y"], x * 2.0)
    # jit caches per input shape: 12>8 split into an 8-bucket + a 4-bucket
    assert s._jit_predict._cache_size() == 2


def test_repository_checkpoint_roundtrip(tmp_path):
    from kubeflow_tpu.runtime.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    trained = {"params": {"w": jnp.full((4,), 3.0)}}
    mgr.save(7, trained, force=True)
    mgr.wait()
    mgr.close()

    repo = ModelRepository()
    s = repo.load("double", "double", checkpoint_dir=str(tmp_path / "ckpt"))
    assert s.version == 7
    out = s.predict(np.ones((2, 4), np.float32))
    np.testing.assert_allclose(out["y"], 3.0 * np.ones((2, 4)))


def test_hot_reload_picks_up_new_version(tmp_path):
    """TF-Serving fs-monitor behavior: the trainer writes a newer
    checkpoint, the repository swaps it in; older/absent versions no-op."""
    from kubeflow_tpu.runtime.checkpoint import CheckpointManager
    ckpt = str(tmp_path / "ckpt")
    mgr = CheckpointManager(ckpt)
    mgr.save(1, {"params": {"w": jnp.full((4,), 2.0)}}, force=True)
    mgr.wait()

    repo = ModelRepository()
    s = repo.load("double", "double", checkpoint_dir=ckpt)
    assert s.version == 1
    assert not repo.reload("double")  # nothing newer yet

    mgr.save(5, {"params": {"w": jnp.full((4,), 10.0)}}, force=True)
    mgr.wait()
    mgr.close()
    assert repo.reload("double")
    assert s.version == 5
    out = s.predict(np.ones((2, 4), np.float32))
    np.testing.assert_allclose(out["y"], 10.0 * np.ones((2, 4)))
    # no checkpoint source → reload is a no-op, not an error
    repo2 = ModelRepository()
    repo2.load("fresh", "double")
    assert not repo2.reload("fresh")


def test_reload_from_trainer_trainstate_checkpoint(tmp_path):
    """The real watch flow: the TRAINER writes full TrainState checkpoints
    (not params-only dicts); the server must extract the params subtree."""
    from kubeflow_tpu.runtime.worker import train
    ckpt = str(tmp_path / "ckpt")
    train(workload="transformer", steps=2, global_batch=8,
          checkpoint_dir=ckpt, checkpoint_every=1, sync_every=1,
          workload_kwargs={})

    from kubeflow_tpu.serving.servable import ModelRepository, register_model
    from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig.tiny()
    model = TransformerLM(cfg)

    @register_model("tiny_lm")
    def _tiny_lm():
        from kubeflow_tpu.models.transformer import init_fn
        def init():
            return {"params": init_fn(model, cfg.max_seq_len)(
                jax.random.PRNGKey(0))[0]}
        def predict(variables, tokens):
            return {"next": jnp.argmax(
                model.apply(variables, tokens)[:, -1], axis=-1)}
        return predict, init, {}

    repo = ModelRepository()
    s = repo.load("lm", "tiny_lm", checkpoint_dir=ckpt)
    assert s.version == 2  # restored from the trainer's TrainState
    # trainer writes a newer step → reload extracts params again
    train(workload="transformer", steps=4, global_batch=8,
          checkpoint_dir=ckpt, checkpoint_every=1, sync_every=1,
          workload_kwargs={})
    assert repo.reload("lm")
    assert s.version == 4


def test_server_before_trainer_picks_up_first_checkpoint(tmp_path):
    """Server starts on an empty model path (version 0 placeholder); the
    trainer's FIRST checkpoint — even step 1 — must be adopted."""
    from kubeflow_tpu.runtime.checkpoint import CheckpointManager
    ckpt = str(tmp_path / "ckpt")
    repo = ModelRepository()
    s = repo.load("double", "double", checkpoint_dir=ckpt)
    assert s.version == 0  # placeholder: serving init params
    mgr = CheckpointManager(ckpt)
    mgr.save(1, {"params": {"w": jnp.full((4,), 9.0)}}, force=True)
    mgr.wait(); mgr.close()
    assert repo.reload("double")
    assert s.version == 1
    out = s.predict(np.ones((1, 4), np.float32))
    np.testing.assert_allclose(out["y"], 9.0 * np.ones((1, 4)))


def test_polling_reloads_in_background(tmp_path):
    import time as _time
    from kubeflow_tpu.runtime.checkpoint import CheckpointManager
    ckpt = str(tmp_path / "ckpt")
    mgr = CheckpointManager(ckpt)
    mgr.save(1, {"params": {"w": jnp.full((4,), 2.0)}}, force=True)
    mgr.wait()
    repo = ModelRepository()
    s = repo.load("double", "double", checkpoint_dir=ckpt)
    repo.start_polling(interval_s=0.05)
    try:
        mgr.save(9, {"params": {"w": jnp.full((4,), 4.0)}}, force=True)
        mgr.wait()
        mgr.close()
        deadline = _time.time() + 10
        while s.version != 9 and _time.time() < deadline:
            _time.sleep(0.05)
        assert s.version == 9
    finally:
        repo.stop_polling()


def test_repository_unknown_model():
    repo = ModelRepository()
    with pytest.raises(KeyError):
        repo.load("x", "nope")
    with pytest.raises(KeyError):
        repo.get("missing")


def test_microbatcher_concurrent():
    s = _servable()
    b = MicroBatcher(s, max_batch=32, max_latency_ms=20)
    results = {}

    def worker(i):
        x = np.full((2, 4), float(i), np.float32)
        results[i] = b.predict(x)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    b.shutdown()
    for i in range(8):
        np.testing.assert_allclose(results[i]["y"], 2.0 * i)


def test_microbatcher_error_propagates():
    s = _servable()
    b = MicroBatcher(s, max_latency_ms=1)
    fut = b.submit(np.ones((1, 3), np.float32))  # wrong dim → error
    with pytest.raises(Exception):
        fut.result(timeout=10)
    b.shutdown()


@pytest.fixture()
def server():
    repo = ModelRepository()
    repo.load("mnist", "double")
    srv = ModelServer(repo, host="127.0.0.1", port=0, max_latency_ms=1)
    srv.start()
    yield srv
    srv.stop()


def _get(srv, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}") as r:
        return r.status, json.loads(r.read())


def _post(srv, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_rest_status_and_metadata(server):
    code, status = _get(server, "/v1/models/mnist")
    assert code == 200
    assert status["model_version_status"][0]["state"] == "AVAILABLE"
    code, meta = _get(server, "/v1/models/mnist/metadata")
    assert meta["model_spec"]["name"] == "mnist"
    code, health = _get(server, "/healthz")
    assert health == {"status": "ok"}


def test_rest_predict(server):
    code, resp = _post(server, "/v1/models/mnist:predict",
                       {"instances": [[1, 2, 3, 4], [5, 6, 7, 8]],
                        "dtype": "float32"})
    assert code == 200
    np.testing.assert_allclose(resp["predictions"]["y"],
                               [[2, 4, 6, 8], [10, 12, 14, 16]])


def test_rest_predict_unknown_model(server):
    code, resp = _post(server, "/v1/models/nope:predict",
                       {"instances": [[1, 2, 3, 4]]})
    assert code == 404
    assert "error" in resp


def test_rest_metrics_after_traffic(server):
    _post(server, "/v1/models/mnist:predict",
          {"instances": [[1, 2, 3, 4]], "dtype": "float32"})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics") as r:
        text = r.read().decode()
    assert 'kubeflow_model_request_count{model="mnist"}' in text


def test_batch_predict_jsonl_and_npy(tmp_path):
    s = _servable()
    jsonl = tmp_path / "in.jsonl"
    with jsonl.open("w") as f:
        for i in range(5):
            f.write(json.dumps({"instance": [float(i)] * 4}) + "\n")
    np.save(tmp_path / "in.npy",
            np.ones((3, 4), np.float32))

    out = tmp_path / "preds.jsonl"
    summary = run_batch_predict(
        s, [str(tmp_path / "in.jsonl"), str(tmp_path / "in.npy")],
        str(out), batch_size=4, input_dtype="float32")
    assert summary["instances"] == 8
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    preds = [l for l in lines if "prediction" in l]
    assert len(preds) == 8
    np.testing.assert_allclose(preds[1]["prediction"]["y"], [2.0] * 4)
    assert lines[-1]["summary"]["instances"] == 8


def test_batch_predict_no_inputs(tmp_path):
    s = _servable()
    with pytest.raises(FileNotFoundError):
        run_batch_predict(s, [str(tmp_path / "*.npy")], str(tmp_path / "o"))


class TestServingClient:
    """inception-client/label.py analog: the CLI client drives the live
    model server REST surface end-to-end."""

    def test_predict_and_topk(self, tmp_path, capsys):
        from kubeflow_tpu.serving.client import main, top_k
        import numpy as np
        repo = ModelRepository()
        repo.load("mnist", "double")
        srv = ModelServer(repo, host="127.0.0.1", port=0, max_latency_ms=1)
        srv.start()
        try:
            npy = tmp_path / "x.npy"
            np.save(npy, np.array([1.0, 3.0, 2.0, 0.5], np.float32))
            rc = main(["--server", f"127.0.0.1:{srv.port}",
                       "--model", "mnist", "--npy", str(npy),
                       "--top-k", "2"])
            assert rc == 0
            out = capsys.readouterr().out.strip().splitlines()
            assert len(out) == 2
            # "double" model doubles the input → class 1 (value 6) first
            assert out[0].split()[-1] == "1"
        finally:
            srv.stop()

    def test_topk_with_labels(self):
        from kubeflow_tpu.serving.client import top_k
        out = top_k([0.1, 5.0, 1.0], k=2, labels=["cat", "dog", "fish"])
        assert out[0]["label"] == "dog"
        assert abs(sum(o["score"] for o in top_k([0.1, 5.0, 1.0], k=3)) - 1.0) < 1e-5


def test_warmup_compiles_buckets_without_polluting_stats():
    """SURVEY §7 hard part (e): cold-start — every padded bucket is
    compiled at load, so the first real request never pays XLA compile,
    and warmup traffic does not count in serving stats."""
    s = _servable()
    s.max_batch = 8
    buckets = s.warmup()
    assert buckets == [1, 2, 4, 8]
    assert s._jit_predict._cache_size() == 4  # one executable per bucket
    assert s.metadata()["stats"]["request_count"] == 0
    # a real request on any bucket is now a cache hit
    out = s.predict(np.ones((3, 4), np.float32))
    np.testing.assert_allclose(out["y"], 2.0 * np.ones((3, 4)))
    assert s._jit_predict._cache_size() == 4  # padded to bucket 4: no compile
    assert s.metadata()["stats"]["request_count"] == 1


def test_warmup_no_signature_is_noop():
    from kubeflow_tpu.serving.servable import Servable
    s = Servable(name="x", predict_fn=lambda p, x: {"y": x},
                 params={}, input_signature={})
    assert s.warmup() == []
    # shape-less / dynamic signatures are no-ops too, never KeyErrors
    s2 = Servable(name="y", predict_fn=lambda p, x: {"y": x},
                  params={}, input_signature={"inputs": {"dtype": "int32"}})
    assert s2.warmup() == []


def test_warmup_covers_non_power_of_two_cap():
    s = _servable()
    s.max_batch = 12
    assert s.warmup() == [1, 2, 4, 8, 12]  # the cap bucket is warmed too


def test_rewarmup_preserves_serving_stats():
    s = _servable()
    s.max_batch = 4
    s.predict(np.ones((2, 4), np.float32))
    assert s.metadata()["stats"]["request_count"] == 1
    s.warmup()  # re-warm after serving: counters must not move backwards
    assert s.metadata()["stats"]["request_count"] == 1


def test_rewarm_under_traffic_keeps_concurrent_request_stats():
    """ADVICE r3: a re-warm concurrent with live traffic must not discard
    stats increments from real requests landing during the warmup window
    (the old snapshot/restore did)."""
    import threading

    s = _servable()
    s.max_batch = 8
    s.warmup()
    n_requests = 20
    stop = threading.Event()

    def traffic():
        for _ in range(n_requests):
            s.predict(np.ones((3, 4), np.float32))
        stop.set()

    t = threading.Thread(target=traffic)
    t.start()
    while not stop.is_set():  # re-warm repeatedly while traffic flows
        s.warmup()
    t.join()
    assert s.metadata()["stats"]["request_count"] == n_requests
