"""Control-plane telemetry tests (ISSUE 20).

Four tiers, mirroring the module's layering (obs/controlplane.py):
- audit accounting: AuditingKubeClient vs FakeCluster's server-side
  ledger — every request, list object count, and list byte total must
  reconcile EXACTLY, per component, failures included; two writers on
  one cluster must never cross-charge;
- pass profiling: ctrl_pass phase attribution, write amplification,
  no-op classification, reentrancy, span sampling pins (write-bearing
  passes are NEVER sampled away);
- runtime attribution: leadership-churn relist records (failover =
  exactly one leader-gain record on the gaining replica), workqueue
  dwell, the REST apiserver's header-carried attribution;
- cardinality: kftpu_obs_series_total and the 200-job churn leak
  regression (kftpu_job_phase, job ledgers, replica prune).

The 10k-job/1k-node scale ladder rides bench.py --mode ctrl-scale.
"""

import math
import time

import pytest

from kubeflow_tpu.cluster.fake import FakeCluster
from kubeflow_tpu.controllers.runtime import Controller, Manager
from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
from kubeflow_tpu.obs import controlplane as ctrlobs
from kubeflow_tpu.obs import registry as obsreg
from kubeflow_tpu.obs import trace as obstrace
from kubeflow_tpu.scheduler.core import SliceScheduler

pytestmark = pytest.mark.ctrlobs

TPU_AV = "tpu.kubeflow.org/v1alpha1"


@pytest.fixture(autouse=True)
def _fresh_telemetry(monkeypatch):
    """Every test starts from a zeroed registry, sampling counters, and
    span-writer cache — and leaves none of them behind."""
    monkeypatch.delenv(obstrace.SPAN_PATH_ENV, raising=False)
    monkeypatch.delenv(ctrlobs.CTRL_SPAN_SAMPLE_ENV, raising=False)
    obsreg.reset_default_registry()
    ctrlobs.reset_span_sampling()
    obstrace.reset_default_tracers()
    yield
    obstrace.reset_default_tracers()
    obsreg.reset_default_registry()
    ctrlobs.reset_span_sampling()


def tpujob(name, ns="kubeflow", policy=True):
    spec = {
        "replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [
                {"name": "jax", "image": "trainer:v1"}]}}}},
        "runPolicy": {"backoffLimit": 2},
    }
    if policy:
        spec["schedulingPolicy"] = {"queue": "default", "priority": 0,
                                    "preemptible": True}
    return {"apiVersion": TPU_AV, "kind": "TPUJob",
            "metadata": {"name": name, "namespace": ns}, "spec": spec}


# ------------------------------------------------------- audit accounting


class TestAuditAccounting:
    def test_client_and_server_reconcile_exactly(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        cli = ctrlobs.AuditingKubeClient(cluster, "sched")
        cli.list("v1", "Node")
        node = cli.get("v1", "Node", "", "tpu-pool-v5e-8-0")
        cli.patch("v1", "Node", "", node["metadata"]["name"],
                  {"metadata": {"labels": {"x": "y"}}})
        with pytest.raises(Exception):
            cli.get("v1", "Node", "", "no-such-node")
        assert ctrlobs.audit_mismatches({"sched": cli},
                                        cluster.audit) == []
        # the failed get COUNTED on both sides (the server processed it)
        assert cli.totals()["requests"][("get", "Node")] == 2

    def test_list_payload_objects_and_bytes_match(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")   # 2 hosts
        cli = ctrlobs.AuditingKubeClient(cluster, "sched")
        out = cli.list("v1", "Node")
        want = ctrlobs.payload_bytes(out)
        assert cli.totals()["list_objects"]["Node"] == len(out) == 2
        assert cli.totals()["list_bytes"]["Node"] == want
        st = cluster.audit.totals()
        assert st["list_objects"][("sched", "Node")] == 2
        assert st["list_bytes"][("sched", "Node")] == want

    def test_two_writers_never_cross_charge(self):
        """The operator and the scheduler on ONE cluster: the server's
        ledger keeps their rows apart — pod creates land on the
        operator's account, binding patches on the scheduler's — and
        both reconcile exactly at once."""
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        sched = mgr.add(SliceScheduler())
        op = mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob("train"))
        for _ in range(4):
            mgr.run_pending()
            cluster.tick()
        try:
            assert len(cluster.list("v1", "Pod", "kubeflow")) == 2
            clients = {c._name(): c.client for c in mgr.controllers}
            assert set(clients) == {"scheduler", "tpujob"}
            assert ctrlobs.audit_mismatches(clients,
                                            cluster.audit) == []
            req = cluster.audit.totals()["requests"]
            assert req[("tpujob", "create", "Pod")] == 2
            assert ("scheduler", "create", "Pod") not in req
            assert req[("scheduler", "patch", "TPUJob")] >= 1
            # ... and the registry carries the same split
            fam = obsreg.default_registry().family(
                "kftpu_ctrl_requests_total")
            by_comp = {k: int(c.value)
                       for k, c in fam.children().items()}
            assert by_comp[("tpujob", "create", "Pod")] == 2
            assert ("scheduler", "create", "Pod") not in by_comp
        finally:
            sched.stop()
            op.stop()

    def test_unattributed_writes_ignored_by_reconciliation(self):
        cluster = FakeCluster()
        cli = ctrlobs.AuditingKubeClient(cluster, "sched")
        cli.list("v1", "Node")
        # hand-of-god helper traffic: server-side rows exist, but under
        # "unattributed" — no client ledger to reconcile against
        cluster.create(tpujob("direct"))
        st = cluster.audit.totals()
        assert st["requests"][(ctrlobs.UNATTRIBUTED, "create",
                               "TPUJob")] == 1
        assert ctrlobs.audit_mismatches({"sched": cli},
                                        cluster.audit) == []

    def test_mismatch_reported_both_directions(self):
        cluster = FakeCluster()
        cli = ctrlobs.AuditingKubeClient(cluster, "sched")
        cli.list("v1", "Node")
        # a server row the client never issued (cross-charged traffic)
        with ctrlobs.attributed("sched"):
            cluster.create(tpujob("forged"))
        lines = ctrlobs.audit_mismatches({"sched": cli}, cluster.audit)
        assert any("create/TPUJob" in line for line in lines)

    def test_vocabulary_shape(self):
        assert ctrlobs.MUTATING_VERBS == frozenset((
            "create", "update", "update_status", "patch", "delete"))
        assert ctrlobs.VERB_LIST not in ctrlobs.MUTATING_VERBS
        assert ctrlobs.VERB_WATCH not in ctrlobs.MUTATING_VERBS
        assert ctrlobs.PHASES == ("snapshot", "health-pass", "plan",
                                  "writes", "warm-pass")
        assert ctrlobs.RELIST_REASONS == ("initial", "resync",
                                          "leader-gain")


# -------------------------------------------------------- pass profiling


class TestPassProfiling:
    def test_phase_attribution_accumulates(self):
        with ctrlobs.ctrl_pass("sched") as pctx:
            with pctx.phase(ctrlobs.PHASE_SNAPSHOT):
                time.sleep(0.01)
            with pctx.phase(ctrlobs.PHASE_PLAN):
                pass
            with pctx.phase(ctrlobs.PHASE_SNAPSHOT):   # re-entry adds
                time.sleep(0.01)
        assert pctx.phases[ctrlobs.PHASE_SNAPSHOT][0] >= 0.02
        assert set(pctx.phases) == {ctrlobs.PHASE_SNAPSHOT,
                                    ctrlobs.PHASE_PLAN}
        with pytest.raises(ValueError):
            with pctx.phase("not-a-phase"):
                pass

    def test_write_amplification_counts_distinct_objects(self):
        cluster = FakeCluster()
        cluster.add_node("n0", {"cpu": 1})
        cluster.add_node("n1", {"cpu": 1})
        cli = ctrlobs.AuditingKubeClient(cluster, "sched")
        with ctrlobs.ctrl_pass("sched") as pctx:
            cli.patch("v1", "Node", "", "n0",
                      {"metadata": {"labels": {"a": "1"}}})
            cli.patch("v1", "Node", "", "n0",
                      {"metadata": {"labels": {"a": "2"}}})
            cli.patch("v1", "Node", "", "n1",
                      {"metadata": {"labels": {"a": "1"}}})
        assert pctx.mutating_calls == 3
        assert len(pctx.changed) == 2
        assert pctx.write_amplification == pytest.approx(1.5)
        g = obsreg.default_registry().family(
            "kftpu_ctrl_write_amplification")
        assert g.children()[("sched",)].value == pytest.approx(1.5)

    def test_failed_mutation_amplifies_without_changing(self):
        cluster = FakeCluster()
        cluster.add_node("n0", {"cpu": 1})
        cli = ctrlobs.AuditingKubeClient(cluster, "sched")
        with ctrlobs.ctrl_pass("sched") as pctx:
            cli.patch("v1", "Node", "", "n0",
                      {"metadata": {"labels": {"a": "1"}}})
            with pytest.raises(Exception):
                cli.patch("v1", "Node", "", "ghost",
                          {"metadata": {"labels": {"a": "1"}}})
        # numerator counts the failed call; denominator does not
        assert pctx.mutating_calls == 2
        assert len(pctx.changed) == 1
        assert pctx.write_amplification == pytest.approx(2.0)

    def test_noop_and_write_outcomes_counted(self):
        cluster = FakeCluster()
        cluster.add_node("n0", {"cpu": 1})
        cli = ctrlobs.AuditingKubeClient(cluster, "sched")
        with ctrlobs.ctrl_pass("sched"):
            cli.list("v1", "Node")          # reads only: a no-op pass
        with ctrlobs.ctrl_pass("sched"):
            cli.patch("v1", "Node", "", "n0",
                      {"metadata": {"labels": {"b": "1"}}})
        fam = obsreg.default_registry().family("kftpu_ctrl_passes_total")
        by_outcome = {k: int(c.value) for k, c in fam.children().items()}
        assert by_outcome[("sched", ctrlobs.OUTCOME_NOOP)] == 1
        assert by_outcome[("sched", ctrlobs.OUTCOME_WRITE)] == 1

    def test_reentrant_pass_joins_not_double_counts(self):
        with ctrlobs.ctrl_pass("op", key="a/b") as outer:
            with ctrlobs.ctrl_pass("op") as inner:
                assert inner is outer
        fam = obsreg.default_registry().family("kftpu_ctrl_passes_total")
        assert sum(int(c.value) for c in fam.children().values()) == 1

    def test_pass_stats_rollup(self):
        with ctrlobs.ctrl_pass("sched"):
            pass
        with ctrlobs.ctrl_pass("sched") as pctx:
            pctx.note_request(ctrlobs.VERB_PATCH, "Node", ok=True,
                              changed_key=("Node", "", "n0"))
        ctrlobs.record_relist("sched", ctrlobs.RELIST_INITIAL, 7)
        stats = ctrlobs.pass_stats()["sched"]
        assert stats["passes"] == 2
        assert stats["noopPasses"] == 1
        assert stats["noopFraction"] == pytest.approx(0.5)
        assert stats["writeAmplification"] == pytest.approx(1.0)
        assert stats["relists"] == 1 and stats["relistObjects"] == 7
        with pytest.raises(ValueError):
            ctrlobs.record_relist("sched", "vibes", 1)

    def test_quantile_from_buckets_interpolates(self):
        buckets = {0.1: 10, 0.5: 20, math.inf: 20}
        assert ctrlobs.quantile_from_buckets(buckets, 0.5) == \
            pytest.approx(0.1)
        assert ctrlobs.quantile_from_buckets(buckets, 0.75) == \
            pytest.approx(0.3)
        assert ctrlobs.quantile_from_buckets({}, 0.5) == 0.0


# --------------------------------------------------------- span sampling


class TestSpanSampling:
    def _emit_passes(self, tmp_path, monkeypatch, n, write_every=None,
                     sample="5"):
        monkeypatch.setenv(obstrace.SPAN_PATH_ENV,
                           str(tmp_path / "spans.jsonl"))
        monkeypatch.setenv(ctrlobs.CTRL_SPAN_SAMPLE_ENV, sample)
        ctrlobs.reset_span_sampling()
        for i in range(n):
            with ctrlobs.ctrl_pass("sched") as pctx:
                with pctx.phase(ctrlobs.PHASE_SNAPSHOT):
                    pass
                with pctx.phase(ctrlobs.PHASE_PLAN):
                    pass
                if write_every and i % write_every == 0:
                    pctx.note_request(
                        ctrlobs.VERB_PATCH, "TPUJob", ok=True,
                        changed_key=("TPUJob", "kubeflow", f"j{i}"))
        obstrace.reset_default_tracers()   # flush writers
        return obstrace.load_spans(str(tmp_path / "spans.jsonl"))

    def test_noop_passes_sampled_one_in_n(self, tmp_path, monkeypatch):
        spans = self._emit_passes(tmp_path, monkeypatch, 10, sample="5")
        passes = [s for s in spans
                  if s["name"] == ctrlobs.CTRL_PASS_SPAN]
        # deterministic 1-in-5: passes 0 and 5 emit
        assert len(passes) == 2
        assert all(s["attrs"]["outcome"] == "noop" for s in passes)
        assert all(s["attrs"]["sample_n"] == 5 for s in passes)

    def test_write_bearing_passes_never_sampled_away(self, tmp_path,
                                                     monkeypatch):
        spans = self._emit_passes(tmp_path, monkeypatch, 10,
                                  write_every=1, sample="1000000")
        passes = [s for s in spans
                  if s["name"] == ctrlobs.CTRL_PASS_SPAN]
        assert len(passes) == 10   # every single one, sampling ignored
        assert all(s["attrs"]["outcome"] == "write" for s in passes)

    def test_sample_one_emits_every_noop(self, tmp_path, monkeypatch):
        spans = self._emit_passes(tmp_path, monkeypatch, 4, sample="1")
        passes = [s for s in spans
                  if s["name"] == ctrlobs.CTRL_PASS_SPAN]
        assert len(passes) == 4

    def test_pass_reconstructs_phase_by_phase_from_jsonl(
            self, tmp_path, monkeypatch):
        spans = self._emit_passes(tmp_path, monkeypatch, 1,
                                  write_every=1)
        parent = next(s for s in spans
                      if s["name"] == ctrlobs.CTRL_PASS_SPAN)
        assert parent["trace_id"].startswith(
            ctrlobs.CTRL_PASS_TRACE_PREFIX)
        recon = obstrace.reconstruct(str(tmp_path / "spans.jsonl"),
                                     parent["trace_id"])
        assert recon["names"][0] == ctrlobs.CTRL_PASS_SPAN
        assert recon["names"][1:] == [ctrlobs.PHASE_SNAPSHOT,
                                      ctrlobs.PHASE_PLAN]
        # children nest inside the parent window
        # serialized timestamps are rounded — allow ms-level slack
        p = recon["events"][0]
        for child in recon["events"][1:]:
            assert child["start"] >= p["start"] - 1e-3
            assert child["end"] <= p["end"] + 1e-3


# ------------------------------------------- runtime/REST attribution


class TestRuntimeAttribution:
    def test_manager_add_records_initial_relist(self):
        cluster = FakeCluster()
        cluster.create(tpujob("a"))
        cluster.create(tpujob("b"))
        mgr = Manager(cluster)
        op = mgr.add(TrainingJobReconciler("TPUJob"))
        try:
            assert [r["reason"] for r in op.relists] == \
                [ctrlobs.RELIST_INITIAL]
            assert op.relists[0]["objects"] == 2
        finally:
            op.stop()

    def test_resync_records_relist(self):
        cluster = FakeCluster()
        cluster.create(tpujob("a"))
        ctrl = Controller(reconciler=TrainingJobReconciler("TPUJob"),
                          client=cluster, resync_interval=0.01)
        try:
            ctrl.pump_events()
            resyncs = [r for r in ctrl.relists
                       if r["reason"] == ctrlobs.RELIST_RESYNC]
            assert len(resyncs) == 1 and resyncs[0]["objects"] == 1
        finally:
            ctrl.stop()

    def test_failover_exactly_one_leader_gain_on_gaining_replica(self):
        from kubeflow_tpu.cluster import lease as L
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        cluster.create(tpujob("train"))

        def replica(ident):
            elector = L.LeaderElector(client=cluster, identity=ident,
                                      name="op", duration_s=0.25)
            ctrl = Controller(
                reconciler=TrainingJobReconciler("TPUJob"),
                client=L.FencedKubeClient(cluster, elector),
                elector=elector)
            ctrl.bind_watches()
            return elector, ctrl

        el_a, ctrl_a = replica("a")
        el_b, ctrl_b = replica("b")
        try:
            for _ in range(3):
                ctrl_a.run_pending()
                ctrl_b.run_pending()
                cluster.tick()
            assert el_a.is_leader and not el_b.is_leader
            gains_a = [r for r in ctrl_a.relists
                       if r["reason"] == ctrlobs.RELIST_LEADER_GAIN]
            assert len(gains_a) == 1       # winning the FIRST election
            assert ctrl_b.relists == []    # the standby adopted nothing
            # leader stops renewing; the standby steals after expiry
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not el_b.is_leader:
                ctrl_b.run_pending()
                cluster.tick()
                time.sleep(0.02)
            assert el_b.is_leader
            gains_b = [r for r in ctrl_b.relists
                       if r["reason"] == ctrlobs.RELIST_LEADER_GAIN]
            assert len(gains_b) == 1       # EXACTLY one adopt-the-world
            assert gains_b[0]["objects"] == 1
            # the deposed replica gained nothing new
            assert len([r for r in ctrl_a.relists
                        if r["reason"] ==
                        ctrlobs.RELIST_LEADER_GAIN]) == 1
        finally:
            ctrl_a.stop()
            ctrl_b.stop()

    def test_workqueue_dwell_observed(self):
        cluster = FakeCluster()
        cluster.create(tpujob("train", policy=False))
        ctrl = Controller(reconciler=TrainingJobReconciler("TPUJob"),
                          client=cluster)
        try:
            ctrl.enqueue_existing()
            time.sleep(0.01)
            assert ctrl.process_one()
            fam = obsreg.default_registry().family(
                "kftpu_ctrl_workqueue_dwell_seconds")
            buckets = fam.children()[("tpujob",)].bucket_counts()
            assert buckets[math.inf] == 1
            assert ctrl.queue.last_dwell_s >= 0.01
        finally:
            ctrl.stop()

    def test_rest_apiserver_reconciles_with_component_header(self):
        from kubeflow_tpu.cluster.apiserver import ClusterAPIServer
        from kubeflow_tpu.cluster.http_client import HttpKubeClient
        backend = FakeCluster()
        srv = ClusterAPIServer(backend, port=0)
        srv.start()
        try:
            inner = HttpKubeClient(f"http://127.0.0.1:{srv.port}")
            cli = ctrlobs.AuditingKubeClient(inner, "op")
            assert inner._headers[ctrlobs.COMPONENT_HEADER] == "op"
            cli.create({"apiVersion": "v1", "kind": "ConfigMap",
                        "metadata": {"name": "cm", "namespace": "d"},
                        "data": {"k": "v"}})
            cli.list("v1", "ConfigMap", "d")
            cli.get("v1", "ConfigMap", "d", "cm")
            cli.patch("v1", "ConfigMap", "d", "cm",
                      {"data": {"k": "v2"}})
            cli.delete("v1", "ConfigMap", "d", "cm")
            assert ctrlobs.audit_mismatches({"op": cli},
                                            srv.audit) == []
        finally:
            srv.stop()

    def test_rest_watch_counts_stream_deliveries(self):
        from kubeflow_tpu.cluster.apiserver import ClusterAPIServer
        from kubeflow_tpu.cluster.http_client import HttpKubeClient
        backend = FakeCluster()
        srv = ClusterAPIServer(backend, port=0)
        srv.start()
        try:
            cli = ctrlobs.AuditingKubeClient(
                HttpKubeClient(f"http://127.0.0.1:{srv.port}"), "op")
            w = cli.watch("v1", "ConfigMap")
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and \
                    ("op", "watch", "ConfigMap") not in \
                    srv.audit.totals()["requests"]:
                time.sleep(0.02)
            cli.create({"apiVersion": "v1", "kind": "ConfigMap",
                        "metadata": {"name": "cm", "namespace": "d"},
                        "data": {}})
            got = None
            while time.monotonic() < deadline and got is None:
                got = w.get(timeout=0.1)
            assert got is not None
            assert srv.audit.totals()["requests"][
                ("op", "watch", "ConfigMap")] == 1
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline and \
                    srv.audit.totals()["watch_delivered"].get(
                        "ConfigMap", 0) < 1:
                time.sleep(0.02)
            assert srv.audit.totals()["watch_delivered"][
                "ConfigMap"] >= 1
            w.close()
        finally:
            srv.stop()

    def test_fake_cluster_watch_fanout(self):
        cluster = FakeCluster()
        a = cluster.watch("v1", "ConfigMap")
        b = cluster.watch("v1", "ConfigMap")
        cluster.create({"apiVersion": "v1", "kind": "ConfigMap",
                        "metadata": {"name": "cm", "namespace": "d"},
                        "data": {}})
        assert cluster.audit.fanout("ConfigMap") == pytest.approx(2.0)
        a.close()
        b.close()


# ----------------------------------------------------- series cardinality


class TestSeriesCardinality:
    def test_series_totals_gauge_counts_every_family(self):
        obsreg.counter("kftpu_t_total", "t",
                       labels=("a",)).labels(a="1").inc()
        obsreg.gauge("kftpu_t_g", "t").set(1)
        counts = obsreg.export_series_totals()
        assert counts["kftpu_t_total"] == 1
        assert counts["kftpu_t_g"] == 1
        # the self-series: one row per family, itself included
        assert counts[obsreg.OBS_SERIES_FAMILY] == len(counts)
        fam = obsreg.default_registry().family(obsreg.OBS_SERIES_FAMILY)
        assert len(fam.children()) == len(counts)

    def test_series_totals_drops_stale_family_rows(self):
        g = obsreg.gauge("kftpu_t_g", "t", labels=("x",))
        g.labels(x="1").set(1)
        obsreg.export_series_totals()
        g.remove(x="1")
        counts = obsreg.export_series_totals()
        assert counts["kftpu_t_g"] == 0
        # twice more: the export is idempotent, not self-growing
        first = dict(obsreg.export_series_totals())
        assert obsreg.export_series_totals() == first

    def test_200_job_churn_does_not_leak_series(self):
        """The leak regression the ISSUE pins: 200 jobs through the
        REAL create → bind → run → succeed → delete path must leave the
        per-job series families (kftpu_job_phase, the goodput ledgers)
        empty, and the overall cardinality flat between churn halves."""
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        controllers = [mgr.add(SliceScheduler()),
                       mgr.add(TrainingJobReconciler("TPUJob"))]

        def churn(start, n, batch=10):
            for base in range(start, start + n, batch):
                names = [f"j{i}" for i in range(base, base + batch)]
                for name in names:
                    cluster.create(tpujob(name))
                for _ in range(4):
                    mgr.run_pending()
                    cluster.tick()
                for name in names:
                    for pod in cluster.list("v1", "Pod", "kubeflow"):
                        if pod["metadata"]["name"].startswith(
                                name + "-worker"):
                            cluster.set_pod_phase(
                                "kubeflow", pod["metadata"]["name"],
                                "Succeeded")
                mgr.run_pending()
                for name in names:
                    cluster.delete(TPU_AV, "TPUJob", "kubeflow", name)
                mgr.run_pending()

        try:
            churn(0, 100)
            mid = obsreg.export_series_totals()
            churn(100, 100)
            end = obsreg.export_series_totals()
            # per-job families fully pruned
            assert end.get("kftpu_job_phase", 0) == 0
            assert end.get("kftpu_job_goodput_ratio", 0) == 0
            assert end.get("kftpu_job_badput_seconds_total", 0) == 0
            # cardinality FLAT between halves: same families, same
            # counts — 100 more jobs bought zero new series
            assert end == mid
            assert not cluster.list(TPU_AV, "TPUJob", "kubeflow")
        finally:
            for c in controllers:
                c.stop()

    def test_replica_registry_prune_drops_series(self):
        from kubeflow_tpu.serving.replica_state import ReplicaState
        reg = obsreg.default_registry()
        rr = ReplicaState(reg)
        for i in range(20):
            rr.observe_request(f"m{i}", 0.01)
        before = reg.series_counts()["kftpu_serving_requests_total"]
        assert before >= 20
        rr.prune(["m0"])
        counts = obsreg.export_series_totals()
        assert counts["kftpu_serving_requests_total"] < before
        # everything gone → per-model latency series all pruned
        rr.prune([])
        assert reg.series_counts()["kftpu_serving_request_seconds"] == 0

    def test_scale_gauges_exported_by_scheduler_pass(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        controllers = [mgr.add(SliceScheduler())]
        cluster.create(tpujob("train"))
        try:
            mgr.run_pending()
            reg = obsreg.default_registry()
            jobs_g = reg.family("kftpu_sched_pass_jobs_scanned")
            nodes_g = reg.family("kftpu_sched_pass_nodes_scanned")
            assert jobs_g.children()[()].value == 1
            assert nodes_g.children()[()].value == 2
        finally:
            for c in controllers:
                c.stop()
