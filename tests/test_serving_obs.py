"""Serving request-observability tests (ISSUE 11): request-id
propagation HTTP → batcher → servable, per-request ledgers, the
replica health registry + SLO burn rates, bounded-queue shedding,
shadow-traffic attribution, and the dashboard rollup endpoint."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from kubeflow_tpu.obs import goodput as gp
from kubeflow_tpu.obs.registry import Registry
from kubeflow_tpu.obs.trace import load_spans, reconstruct
from kubeflow_tpu.serving.replica_state import (BURN_WINDOWS, ModelSLO,
                                                ReplicaState)
from kubeflow_tpu.serving.request_trace import (REQUEST_ID_HEADER,
                                                ServingObs,
                                                mint_request_id)

pytestmark = pytest.mark.serving_obs


# ------------------------------------------------------------ pure ledger

class TestDecomposeRequest:
    def test_partition_is_exact_with_residual_as_other(self):
        led = gp.decompose_request(0.100, {
            gp.SERVING_QUEUE: 0.020, gp.SERVING_BATCH_FORM: 0.005,
            gp.SERVING_H2D: 0.010, gp.SERVING_DEVICE: 0.050,
            gp.SERVING_PAD_WASTE: 0.005, gp.SERVING_RESPOND: 0.005})
        assert led["goodputSeconds"] == pytest.approx(0.050)
        assert led["badputSeconds"][gp.BADPUT_OTHER] == \
            pytest.approx(0.005)
        total = led["goodputSeconds"] + sum(led["badputSeconds"].values())
        assert total == pytest.approx(led["wallSeconds"])
        assert gp.categories_sum_ok(led)

    def test_full_vocabulary_zeros_not_omissions(self):
        led = gp.decompose_request(1.0, {})
        assert set(led["badputSeconds"]) == \
            set(gp.SERVING_BADPUT_CATEGORIES)
        # nothing attributed → everything is honest residual
        assert led["badputSeconds"][gp.BADPUT_OTHER] == \
            pytest.approx(1.0)
        assert led["goodputRatio"] == 0.0

    def test_zero_wall(self):
        led = gp.decompose_request(0.0, {gp.SERVING_DEVICE: 0.0})
        assert led["wallSeconds"] == 0.0 and led["goodputRatio"] == 0.0

    def test_oversummed_stages_never_negative_other(self):
        # cross-thread clock fuzz can oversum; other clamps at zero
        led = gp.decompose_request(0.010, {gp.SERVING_DEVICE: 0.011})
        assert led["badputSeconds"][gp.BADPUT_OTHER] == 0.0


def _request_span(rid, model, wall, role="primary", outcome="ok",
                  fill=None, slo_p99_ms=None, start=100.0):
    ledger = gp.decompose_request(wall, {gp.SERVING_DEVICE: wall * 0.6,
                                         gp.SERVING_QUEUE: wall * 0.4})
    attrs = {"model": model, "role": role, "outcome": outcome,
             "ledger": ledger}
    if fill is not None:
        attrs["fill"] = fill
    if slo_p99_ms is not None:
        attrs["slo_p99_ms"] = slo_p99_ms
    return {"trace_id": rid, "span_id": rid, "name":
            gp.SERVING_REQUEST_SPAN, "component": "serving",
            "start": start, "end": start + wall, "attrs": attrs}


class TestServingRollup:
    def test_per_model_per_role_rows(self, tmp_path):
        sink = str(tmp_path / "s.jsonl")
        with open(sink, "w") as f:
            for i in range(20):
                f.write(json.dumps(_request_span(
                    f"r{i:02d}", "m1", 0.010 + 0.001 * i, fill=0.9,
                    slo_p99_ms=25.0)) + "\n")
            f.write(json.dumps(_request_span(
                "shadow1", "m2", 0.500, role="shadow")) + "\n")
            f.write(json.dumps(_request_span(
                "err1", "m1", 0.040, outcome="error",
                slo_p99_ms=25.0)) + "\n")
            f.write(json.dumps(_request_span(
                "shed1", "m1", 0.002, outcome="shed",
                slo_p99_ms=25.0)) + "\n")
        roll = gp.serving_rollup(sink)
        assert roll["requests"] == 23
        rows = {(m["model"], m["role"]): m for m in roll["models"]}
        m1 = rows[("m1", "primary")]
        assert m1["requests"] == 22
        assert m1["errors"] == 1 and m1["shed"] == 1
        assert m1["p50Ms"] > 0 and m1["p99Ms"] >= m1["p50Ms"]
        assert m1["meanFill"] == pytest.approx(0.9)
        assert m1["goodputRatio"] == pytest.approx(0.6, abs=0.05)
        assert set(m1["badputSeconds"]) == \
            set(gp.SERVING_BADPUT_CATEGORIES)
        # slowest ids are reconstructible handles, largest first
        assert m1["slowest"][0]["requestId"] == "err1"
        # SLO block: requests over 25ms against the 1% p99 budget
        assert m1["slo"]["targetP99Ms"] == 25.0
        assert m1["slo"]["overTargetRatio"] > 0.01
        assert m1["slo"]["compliant"] is False
        # shadow traffic reports under its own role row
        shadow = rows[("m2", "shadow")]
        assert shadow["requests"] == 1

    def test_empty_sink(self, tmp_path):
        roll = gp.serving_rollup(str(tmp_path / "none.jsonl"))
        assert roll == {"models": [], "requests": 0}


# ------------------------------------------------------- replica registry

class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestReplicaState:
    def _state(self, slo=None, windows=BURN_WINDOWS):
        reg = Registry()
        clock = FakeClock()
        rs = ReplicaState(reg, windows=windows, clock=clock)
        if slo:
            rs.set_slo("m", slo)
        return rs, reg, clock

    def test_rolling_percentiles_and_error_ratio(self):
        rs, reg, clock = self._state()
        for i in range(100):
            rs.observe_request("m", 0.010 + 0.0001 * i,
                               outcome="ok" if i % 10 else "error")
        rs.refresh()
        snap = rs.snapshot()
        row = snap["models"][0]
        assert row["model"] == "m"
        assert 10.0 < row["p50Ms"] < 20.0
        assert row["p99Ms"] >= row["p50Ms"]
        assert row["errorRatio"] == pytest.approx(0.1)
        assert row["lastRequestAgeSeconds"] == 0.0
        text = reg.render()
        assert 'kftpu_serving_p99_seconds{model="m",role="primary"}' \
            in text
        assert 'kftpu_serving_requests_total{model="m",role="primary"' \
            ',outcome="ok"}' in text

    def test_burn_rates_multi_window(self):
        rs, reg, clock = self._state(
            slo=ModelSLO(target_p99_ms=20.0, availability=0.99),
            windows=(60.0, 3600.0))
        # old window: 5% of requests over target, 2% errors
        for i in range(100):
            over = i < 5
            rs.observe_request("m", 0.030 if over else 0.010,
                               outcome="error" if i < 2 else "ok")
        clock.t += 120  # push those outside the 60s window
        for i in range(50):
            rs.observe_request("m", 0.010)
        snap = rs.snapshot()
        burns = snap["models"][0]["burnRates"]
        # 60s window: only the clean recent traffic → burn 0
        assert burns["60s"]["latency"] == 0.0
        assert burns["60s"]["availability"] == 0.0
        # 3600s window: 5/150 over the 1% p99 budget → ~3.3x burn;
        # 2/150 errors against the 1% availability budget → ~1.3x
        assert burns["3600s"]["latency"] == pytest.approx(
            (5 / 150) / 0.01, rel=0.01)
        assert burns["3600s"]["availability"] == pytest.approx(
            (2 / 150) / 0.01, rel=0.01)
        rs.refresh()
        assert 'kftpu_serving_slo_burn_rate{model="m",slo="latency",' \
            'window="3600s"}' in reg.render()

    def test_badput_counters_accumulate(self):
        rs, reg, _ = self._state()
        led = gp.decompose_request(0.1, {gp.SERVING_QUEUE: 0.04,
                                         gp.SERVING_DEVICE: 0.05})
        rs.observe_request("m", 0.1, ledger=led)
        rs.observe_request("m", 0.1, ledger=led)
        text = reg.render()
        assert 'kftpu_serving_badput_seconds_total{model="m",' \
            'category="queue"} 0.08' in text

    def test_shadow_role_never_pollutes_primary_series(self):
        rs, reg, _ = self._state(slo=ModelSLO(target_p99_ms=20.0))
        rs.observe_request("m", 0.010)            # fast primary
        rs.observe_request("m", 5.0, role="shadow")   # cold shadow JIT
        rs.refresh()
        snap = rs.snapshot()
        row = snap["models"][0]
        # primary percentiles unaffected by the shadow's 5s outlier
        assert row["p99Ms"] < 100.0
        assert row["roles"]["shadow"]["p99Ms"] >= 5000.0
        # burn rate tracks the PRIMARY only
        assert row["burnRates"]["300s"]["latency"] == 0.0

    def test_prune_removes_all_series(self):
        rs, reg, _ = self._state(slo=ModelSLO(target_p99_ms=20.0))
        rs.observe_request("m", 0.030, ledger=gp.decompose_request(
            0.03, {gp.SERVING_QUEUE: 0.03}))
        rs.observe_request("m", 0.030, role="shadow")
        rs.set_start_kind("m", "warm")
        rs.refresh()
        assert 'model="m"' in reg.render()
        rs.prune(live_models=[])
        assert 'model="m"' not in reg.render()
        assert rs.snapshot()["models"] == []

    def test_queue_provider_polled_at_refresh(self):
        class FakeBatcher:
            def queue_depth(self):
                return 7

            def oldest_wait_s(self):
                return 1.5

        rs, reg, _ = self._state()
        rs.register_queue("m", FakeBatcher())
        rs.refresh()
        text = reg.render()
        assert 'kftpu_serving_queue_depth{model="m"} 7' in text
        assert 'kftpu_serving_oldest_wait_seconds{model="m"} 1.5' in text


# ----------------------------------------------- live server (jit paths)

from kubeflow_tpu.serving import (ModelRepository, ModelServer,  # noqa: E402
                                  Servable)
from kubeflow_tpu.serving.servable import register_model  # noqa: E402


@register_model("sobs_double")
def _build_double(dim: int = 4):
    import jax.numpy as jnp

    def init_params():
        return {"w": jnp.full((dim,), 2.0)}

    def predict(params, x):
        return {"y": x * params["w"]}

    sig = {"inputs": {"shape": [-1, dim], "dtype": "float32"}}
    return predict, init_params, sig


def _server(tmp_path, **kw):
    repo = ModelRepository()
    repo.load("mnist", "sobs_double")
    srv = ModelServer(repo, host="127.0.0.1", port=0, max_latency_ms=1,
                      span_path=str(tmp_path / "spans.jsonl"), **kw)
    srv.start()
    return srv


def _post(srv, path, payload, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(srv, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}") as r:
        return r.status, r.read()


@pytest.mark.compute
class TestRequestIdPropagation:
    def test_inbound_id_honored_and_echoed(self, tmp_path):
        srv = _server(tmp_path, sample_every=1)
        try:
            code, _, headers = _post(
                srv, "/v1/models/mnist:predict",
                {"instances": [[1, 2, 3, 4]], "dtype": "float32"},
                headers={"x-request-id": "req-abc-123"})
            assert code == 200
            assert headers.get("x-request-id") == "req-abc-123"
            spans = load_spans(str(tmp_path / "spans.jsonl"))
            assert spans and all(s["trace_id"] == "req-abc-123"
                                 for s in spans)
        finally:
            srv.stop()

    def test_distinct_ids_minted_otherwise(self, tmp_path):
        srv = _server(tmp_path)
        try:
            ids = set()
            for _ in range(3):
                code, _, headers = _post(
                    srv, "/v1/models/mnist:predict",
                    {"instances": [[1, 2, 3, 4]], "dtype": "float32"})
                assert code == 200
                ids.add(headers.get(REQUEST_ID_HEADER))
            assert len(ids) == 3 and all(ids)
        finally:
            srv.stop()

    def test_same_id_on_every_stage_span(self, tmp_path):
        """The acceptance path: one id stamps every stage across
        HTTP handler → batcher → servable timings, and the timeline
        reconstructs stage-by-stage from the JSONL alone."""
        srv = _server(tmp_path, sample_every=1)
        try:
            rid = "stagetrace01"
            code, _, _ = _post(
                srv, "/v1/models/mnist:predict",
                {"instances": [[1, 2, 3, 4]], "dtype": "float32"},
                headers={REQUEST_ID_HEADER: rid})
            assert code == 200
        finally:
            srv.stop()
        timeline = reconstruct(str(tmp_path / "spans.jsonl"), rid)
        names = timeline["names"]
        for want in ("accept", "queue", "batch-form", "h2d", "device",
                     "drain", "respond", gp.SERVING_REQUEST_SPAN):
            assert want in names, f"missing stage span {want}"

        def in_order(*want):
            i = 0
            for nm in names:
                if i < len(want) and nm == want[i]:
                    i += 1
            return i == len(want)

        assert in_order("accept", "queue", "batch-form", "h2d",
                        "device", "drain", "respond")

    def test_force_sample_header_emits_stage_spans(self, tmp_path):
        """x-request-sample: 1 forces stage spans for exactly this
        request even when the sampling cadence would skip it."""
        srv = _server(tmp_path, sample_every=0)   # summaries only
        try:
            _post(srv, "/v1/models/mnist:predict",
                  {"instances": [[1, 2, 3, 4]], "dtype": "float32"},
                  headers={REQUEST_ID_HEADER: "unsampled"})
            _post(srv, "/v1/models/mnist:predict",
                  {"instances": [[1, 2, 3, 4]], "dtype": "float32"},
                  headers={REQUEST_ID_HEADER: "forced",
                           "x-request-sample": "1"})
        finally:
            srv.stop()
        spans = load_spans(str(tmp_path / "spans.jsonl"))
        by_rid: dict = {}
        for s in spans:
            by_rid.setdefault(s["trace_id"], []).append(s["name"])
        assert by_rid["unsampled"] == [gp.SERVING_REQUEST_SPAN]
        assert "device" in by_rid["forced"]

    def test_grpc_request_id_metadata(self, tmp_path):
        grpc_mod = pytest.importorskip("grpc")
        from kubeflow_tpu.serving import tpu_serving_pb2 as pb
        from kubeflow_tpu.serving.grpc_server import (GrpcPredictServer,
                                                      ndarray_to_tensor,
                                                      predict_stub)
        srv = _server(tmp_path, sample_every=1)
        gsrv = GrpcPredictServer(srv, host="127.0.0.1", port=0)
        gport = gsrv.start()
        channel = grpc_mod.insecure_channel(f"127.0.0.1:{gport}")
        try:
            stub = predict_stub(channel)
            req = pb.PredictRequest()
            req.model_spec.name = "mnist"
            req.inputs["instances"].CopyFrom(ndarray_to_tensor(
                np.ones((2, 4), np.float32)))
            _, call = stub["Predict"].with_call(
                req, metadata=((REQUEST_ID_HEADER, "grpcreq1"),))
            echoed = dict(call.initial_metadata())
            assert echoed.get(REQUEST_ID_HEADER) == "grpcreq1"
        finally:
            channel.close()
            gsrv.stop()
            srv.stop()
        spans = load_spans(str(tmp_path / "spans.jsonl"),
                           trace_id="grpcreq1")
        names = {s["name"] for s in spans}
        assert gp.SERVING_REQUEST_SPAN in names
        assert {"queue", "device", "respond"} <= names

    def test_error_request_still_echoes_id_and_lands_ledger(
            self, tmp_path):
        srv = _server(tmp_path)
        try:
            code, _, headers = _post(
                srv, "/v1/models/mnist:predict",
                {"wrong_key": []}, headers={REQUEST_ID_HEADER: "err1"})
            assert code == 400
            assert headers.get(REQUEST_ID_HEADER) == "err1"
            # 404s echo too
            code, _, headers = _post(
                srv, "/v1/models/ghost:predict", {"instances": [[1]]},
                headers={REQUEST_ID_HEADER: "err2"})
            assert code == 404
            assert headers.get(REQUEST_ID_HEADER) == "err2"
        finally:
            srv.stop()
        spans = load_spans(str(tmp_path / "spans.jsonl"),
                           trace_id="err1")
        summary = [s for s in spans
                   if s["name"] == gp.SERVING_REQUEST_SPAN]
        assert summary and summary[0]["attrs"]["outcome"] == "error"


@pytest.mark.compute
class TestRequestLedger:
    def test_ledger_sums_to_wall_over_http(self, tmp_path):
        srv = _server(tmp_path)
        try:
            for _ in range(4):
                code, _, _ = _post(
                    srv, "/v1/models/mnist:predict",
                    {"instances": [[1, 2, 3, 4], [5, 6, 7, 8],
                                   [1, 1, 1, 1]],
                     "dtype": "float32"})
                assert code == 200
        finally:
            srv.stop()
        spans = load_spans(str(tmp_path / "spans.jsonl"))
        summaries = [s for s in spans
                     if s["name"] == gp.SERVING_REQUEST_SPAN]
        assert len(summaries) == 4
        for s in summaries:
            led = s["attrs"]["ledger"]
            assert gp.categories_sum_ok(led)
            assert set(led["badputSeconds"]) == \
                set(gp.SERVING_BADPUT_CATEGORIES)
            # 3 rows pad to bucket 4 → pad waste recorded, fill 0.75
            assert s["attrs"]["fill"] == pytest.approx(0.75)
            assert led["badputSeconds"][gp.SERVING_PAD_WASTE] >= 0.0

    def test_replica_registry_fed_and_metrics_pruned_on_unload(
            self, tmp_path):
        srv = _server(tmp_path)
        try:
            _post(srv, "/v1/models/mnist:predict",
                  {"instances": [[1, 2, 3, 4]], "dtype": "float32"})
            text = srv.metrics_text()
            assert 'kftpu_serving_requests_total{model="mnist"' in text
            assert "kubeflow_model_request_count" in text  # wire compat
            # unload → every serving series for the model disappears
            with srv.repository._lock:
                del srv.repository._models["mnist"]
            text = srv.metrics_text()
            assert 'model="mnist"' not in text
        finally:
            srv.stop()

    def test_healthz_verbose_contract(self, tmp_path):
        srv = _server(tmp_path)
        srv.set_slo("mnist", ModelSLO(target_p99_ms=1000.0,
                                      availability=0.99))
        try:
            _post(srv, "/v1/models/mnist:predict",
                  {"instances": [[1, 2, 3, 4]], "dtype": "float32"})
            code, body = _get(srv, "/healthz?verbose=1")
            assert code == 200
            snap = json.loads(body)
            row = next(m for m in snap["models"]
                       if m["model"] == "mnist")
            for key in ("p50Ms", "p99Ms", "errorRatio", "queueDepth",
                        "inFlight", "lastRequestAgeSeconds",
                        "startKind", "burnRates", "slo"):
                assert key in row, f"healthz missing {key}"
            assert row["requests"] >= 1
            # plain healthz unchanged (wire compat)
            code, body = _get(srv, "/healthz")
            assert json.loads(body) == {"status": "ok"}
        finally:
            srv.stop()


class _SlowServable:
    """Duck-typed servable: host-sleep device, for queue-pressure tests."""

    name = "slow"
    start_kind = "cold"

    def __init__(self, delay_s=0.15):
        self.delay_s = delay_s

    def predict(self, instances):
        time.sleep(self.delay_s)
        return np.asarray(instances)

    def metadata(self):
        return {"stats": {"request_count": 0, "predict_seconds": 0.0}}


@pytest.mark.compute
class TestBoundedQueue:
    def test_queue_full_sheds_429_and_records_ledger(self, tmp_path):
        repo = ModelRepository()
        repo.add(_SlowServable())
        srv = ModelServer(repo, host="127.0.0.1", port=0, max_batch=1,
                          max_latency_ms=0, max_pending=1,
                          span_path=str(tmp_path / "spans.jsonl"))
        srv.start()
        codes = []

        def fire(i):
            code, _, headers = _post(
                srv, "/v1/models/slow:predict",
                {"instances": [[1.0]]},
                headers={REQUEST_ID_HEADER: f"burst{i}"})
            codes.append((code, headers.get(REQUEST_ID_HEADER)))

        try:
            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
                time.sleep(0.01)
            for t in threads:
                t.join()
        finally:
            metrics = srv.metrics_text()
            srv.stop()
        shed = [c for c, _ in codes if c == 429]
        assert shed, f"no 429s in {codes}"
        assert all(rid and rid.startswith("burst") for _, rid in codes)
        # the shed requests' ledgers landed (outcome=shed, not dropped)
        spans = load_spans(str(tmp_path / "spans.jsonl"))
        shed_spans = [s for s in spans
                      if s["name"] == gp.SERVING_REQUEST_SPAN
                      and s["attrs"]["outcome"] == "shed"]
        assert len(shed_spans) == len(shed)
        for s in shed_spans:
            led = s["attrs"]["ledger"]
            assert gp.categories_sum_ok(led)
            # the shed request's unattributed stretch is charged to
            # queue (the bounded queue turned it away), never to other
            assert led["badputSeconds"][gp.SERVING_QUEUE] > 0.0
            assert led["badputSeconds"][gp.BADPUT_OTHER] == 0.0
        assert "kftpu_serving_shed_total" in metrics

    def test_batcher_queue_depth_and_oldest_age(self):
        from kubeflow_tpu.serving.batcher import (MicroBatcher,
                                                  QueueFullError)
        b = MicroBatcher(_SlowServable(delay_s=0.2), max_batch=1,
                         max_latency_ms=0, max_pending=2)
        futs = [b.submit(np.ones((1, 1))) for _ in range(2)]
        # a third submit may race the loop's collect; pending is bounded
        with pytest.raises((QueueFullError, RuntimeError)):
            for _ in range(4):
                futs.append(b.submit(np.ones((1, 1))))
        assert b.queue_depth() >= 1
        assert b.oldest_wait_s() >= 0.0
        for f in futs:
            f.result(timeout=10)
        assert b.queue_depth() == 0
        assert b.oldest_wait_s() == 0.0
        b.shutdown()


@pytest.mark.compute
class TestShadowObservability:
    def test_shadow_gets_own_span_and_role_series(self, tmp_path):
        from kubeflow_tpu.serving.router import RoutedModel, ShadowRouter
        repo = ModelRepository()
        repo.load("prod", "sobs_double")
        repo.load("canary", "sobs_double")
        srv = ModelServer(repo, host="127.0.0.1", port=0,
                          max_latency_ms=1, sample_every=1,
                          span_path=str(tmp_path / "spans.jsonl"))
        routed = RoutedModel(ShadowRouter("prod", "canary"), repo,
                             name="exp")
        srv.add_router(routed)
        srv.start()
        try:
            code, _, headers = _post(
                srv, "/v1/routers/exp:predict",
                {"instances": [[1, 2, 3, 4]], "dtype": "float32"},
                headers={REQUEST_ID_HEADER: "shadowed1"})
            assert code == 200
            routed.drain_shadow()
            metrics = srv.metrics_text()
        finally:
            srv.stop()
        spans = load_spans(str(tmp_path / "spans.jsonl"))
        summaries = {s["trace_id"]: s for s in spans
                     if s["name"] == gp.SERVING_REQUEST_SPAN}
        primary = summaries["shadowed1"]
        assert primary["attrs"]["model"] == "prod"
        assert primary["attrs"]["role"] == "primary"
        assert primary["attrs"]["router"] == "exp"
        # the shadow copy: derived id, role=shadow, its own ledger
        shadow = summaries["shadowed1-shadow"]
        assert shadow["attrs"]["model"] == "canary"
        assert shadow["attrs"]["role"] == "shadow"
        assert gp.categories_sum_ok(shadow["attrs"]["ledger"])
        # latency series split by role — the cold shadow never lands
        # in the primary's series
        assert 'kftpu_serving_requests_total{model="canary",' \
            'role="shadow",outcome="ok"}' in metrics
        assert 'kftpu_serving_requests_total{model="prod",' \
            'role="primary",outcome="ok"}' in metrics

    def test_shadow_failure_recorded_with_role(self, tmp_path):
        from kubeflow_tpu.serving.router import RoutedModel, ShadowRouter

        class FailShadowRepo:
            def get(self, name):
                class S:
                    def predict(self, x, ctx=None):
                        if name == "bad":
                            raise RuntimeError("shadow down")
                        return np.asarray(x)
                return S()

        obs = ServingObs(span_path=str(tmp_path / "spans.jsonl"),
                         sample_every=0)
        routed = RoutedModel(ShadowRouter("good", "bad"),
                             FailShadowRepo(), name="exp",
                             request_obs=obs)
        ctx = obs.begin("router:exp", request_id="pri1")
        routed.predict(np.ones((1, 2)), ctx=ctx)
        ctx.finish("ok")
        routed.drain_shadow()
        spans = load_spans(str(tmp_path / "spans.jsonl"))
        shadow = [s for s in spans
                  if s["name"] == gp.SERVING_REQUEST_SPAN
                  and s["attrs"]["role"] == "shadow"]
        assert shadow and shadow[0]["attrs"]["outcome"] == "error"


@pytest.mark.compute
class TestServableStats:
    def test_stats_ride_the_obs_registry_wire_compatible(self):
        repo = ModelRepository()
        s = repo.load("m", "sobs_double")
        s.predict(np.ones((2, 4), np.float32))
        # the legacy snapshot shape still serves metadata()
        assert s.metadata()["stats"]["request_count"] == 1
        assert s.metadata()["stats"]["predict_seconds"] > 0
        # ...but the bookkeeper is the obs Registry now
        text = s.registry.render()
        assert 'kubeflow_model_request_count{model="m"} 1' in text
        assert "kubeflow_model_predict_seconds_total" in text

    def test_predict_with_stages_partition(self):
        repo = ModelRepository()
        s = repo.load("m", "sobs_double")
        s.max_batch = 8
        out, stages = s.predict_with_stages(
            np.ones((3, 4), np.float32))
        np.testing.assert_allclose(out["y"], 2.0 * np.ones((3, 4)))
        assert stages["bucket"] == 4 and stages["pad_rows"] == 1
        assert stages["rows"] == 3
        for key in ("h2d_s", "device_s", "drain_s"):
            assert stages[key] >= 0.0
        # oversized split aggregates stages (13 → chunks 8 + 5-pad-to-8)
        out, stages = s.predict_with_stages(
            np.ones((13, 4), np.float32))
        assert out["y"].shape == (13, 4)
        assert stages["rows"] == 13 and stages["pad_rows"] == 3

    def test_start_kind_defaults_cold(self):
        repo = ModelRepository()
        s = repo.load("m", "sobs_double")
        assert s.start_kind == "cold"
        s.warmup()   # no persistent cache in tests → still cold
        assert s.start_kind in ("cold", "warm")


@pytest.mark.compute
class TestBatchPredictTracing:
    def test_run_carries_request_id_and_spans(self, tmp_path,
                                              monkeypatch):
        from kubeflow_tpu.serving.batch_predict import run_batch_predict
        monkeypatch.setenv("KFTPU_SPAN_PATH",
                           str(tmp_path / "spans.jsonl"))
        import kubeflow_tpu.obs.trace as obstrace
        obstrace.reset_default_tracers()
        repo = ModelRepository()
        s = repo.load("m", "sobs_double")
        np.save(tmp_path / "in.npy", np.ones((5, 4), np.float32))
        out = tmp_path / "preds.jsonl"
        summary = run_batch_predict(
            s, [str(tmp_path / "in.npy")], str(out), batch_size=4,
            request_id="batchrun01")
        assert summary["requestId"] == "batchrun01"
        lines = [json.loads(line)
                 for line in out.read_text().splitlines()]
        preds = [ln for ln in lines if "prediction" in ln]
        assert all(p["requestId"] == "batchrun01" for p in preds)
        spans = load_spans(str(tmp_path / "spans.jsonl"))
        summaries = [sp for sp in spans
                     if sp["name"] == gp.SERVING_REQUEST_SPAN]
        assert summaries
        assert summaries[0]["trace_id"].startswith("batchrun01")
        assert summaries[0]["attrs"]["outcome"] == "ok"
        obstrace.reset_default_tracers()


class TestDashboardServingEndpoint:
    def test_api_obs_serving(self, tmp_path, monkeypatch):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.obs.trace import SPAN_PATH_ENV
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        sink = str(tmp_path / "spans.jsonl")
        with open(sink, "w") as f:
            for i in range(5):
                f.write(json.dumps(_request_span(
                    f"d{i}", "resnet50", 0.02, fill=0.8,
                    slo_p99_ms=100.0)) + "\n")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        app = build_dashboard_app(FakeCluster())
        status, body = app.dispatch("GET", "/api/obs/serving", None)
        assert status == 200
        assert body["requests"] == 5
        row = body["models"][0]
        assert row["model"] == "resnet50"
        assert row["slo"]["compliant"] is True
        assert set(row["badputSeconds"]) == \
            set(gp.SERVING_BADPUT_CATEGORIES)

    def test_api_obs_serving_no_sink(self, monkeypatch):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.obs.trace import SPAN_PATH_ENV
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        monkeypatch.delenv(SPAN_PATH_ENV, raising=False)
        app = build_dashboard_app(FakeCluster())
        status, body = app.dispatch("GET", "/api/obs/serving", None)
        assert status == 200 and "note" in body


class TestManifestSLOSchema:
    def test_tpu_serving_renders_slo_and_max_pending(self):
        from kubeflow_tpu.manifests.serving import tpu_serving
        objs = tpu_serving(slo_p99_ms=120.0, slo_availability=0.999,
                           max_pending=128)
        dep = next(o for o in objs if o["kind"] == "Deployment")
        args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--slo-p99-ms=120.0" in args
        assert "--slo-availability=0.999" in args
        assert "--max-pending=128" in args
        # defaults render no SLO flags (wire compat)
        objs = tpu_serving()
        dep = next(o for o in objs if o["kind"] == "Deployment")
        args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
        assert not any(a.startswith("--slo") for a in args)

    def test_server_accepts_slo_plumbing(self):
        """The manifest-rendered knobs land on the server (schema ↔
        CLI ↔ constructor, one contract) — no server start needed."""
        from kubeflow_tpu.serving import http_server as hs
        srv = hs.ModelServer(ModelRepository(), host="127.0.0.1",
                             port=0, max_pending=128, sample_every=0,
                             slos={"m": ModelSLO(target_p99_ms=120.0,
                                                 availability=0.999)})
        assert srv.replica.slo_of("m").target_p99_ms == 120.0
        assert srv.max_pending == 128
        # the CLI flags exist in main()'s surface (grep-level pin)
        import inspect
        src = inspect.getsource(hs.main)
        for flag in ("--slo-p99-ms", "--slo-availability",
                     "--max-pending", "--sample-every", "--span-path"):
            assert flag in src

    def test_mint_request_id_shape(self):
        rid = mint_request_id()
        assert len(rid) == 16 and rid != mint_request_id()
