"""Auto-update bot tests (the reference's
update_jupyter_web_app_test.py covered _replace_parameters; here the
whole loop runs against a real temp git repo)."""

import os
import subprocess

import pytest

from kubeflow_tpu.workflows.image_update import (COMPONENT_SOURCES,
                                                 UpdateResult,
                                                 component_commit,
                                                 replace_version,
                                                 update_component)


def git(repo, *args):
    return subprocess.run(["git", *args], cwd=repo, check=True, text=True,
                          capture_output=True).stdout.strip()


@pytest.fixture
def repo(tmp_path):
    root = str(tmp_path / "repo")
    os.makedirs(os.path.join(root, "kubeflow_tpu/webapps"))
    os.makedirs(os.path.join(root, "kubeflow_tpu/manifests"))
    git(root, "init", "-q")
    git(root, "config", "user.email", "ci@test")
    git(root, "config", "user.name", "ci")
    with open(os.path.join(root, "kubeflow_tpu/webapps/app.py"), "w") as f:
        f.write("print('v1')\n")
    pin = os.path.join(root, "kubeflow_tpu/manifests/notebooks.py")
    with open(pin, "w") as f:
        f.write('"""pins"""\nVERSION = "v0.1.0"\n'
                'JUPYTER_WEB_APP_VERSION = "v0.1.0"\nIMG = "x"\n')
    git(root, "add", ".")
    git(root, "commit", "-q", "-m", "initial")
    return root


class TestReplaceVersion:
    def test_rewrites_and_returns_old(self):
        lines, old = replace_version(
            ['x = 1', 'VERSION = "v0.1.0"', 'y = 2'], "abc123")
        assert old == "v0.1.0"
        assert lines[1] == 'VERSION = "abc123"'

    def test_named_pin_leaves_module_version_alone(self):
        # the bot must retag ONLY its component: the module-wide VERSION
        # (tagging unrelated images) stays untouched
        lines, old = replace_version(
            ['VERSION = "v0.1.0"', 'JUPYTER_WEB_APP_VERSION = "v0.1.0"'],
            "abc123", pin="JUPYTER_WEB_APP_VERSION")
        assert old == "v0.1.0"
        assert lines[0] == 'VERSION = "v0.1.0"'
        assert lines[1] == 'JUPYTER_WEB_APP_VERSION = "abc123"'

    def test_no_pin_raises(self):
        with pytest.raises(ValueError, match="VERSION"):
            replace_version(["x = 1"], "abc")


class TestUpdateComponent:
    def test_full_loop_branch_and_commit(self, repo):
        tag = component_commit(repo, "kubeflow_tpu/webapps")
        result = update_component(repo, "jupyter-web-app")
        assert isinstance(result, UpdateResult)
        assert result.changed
        assert result.new_tag == tag
        assert result.old_tag == "v0.1.0"
        assert result.images == \
            [f"ghcr.io/kubeflow-tpu/jupyter-web-app:{tag}"]
        # pin rewritten on a new branch with one commit; the module-wide
        # VERSION (other images) is untouched
        assert git(repo, "rev-parse", "--abbrev-ref", "HEAD") == \
            f"update-jupyter-web-app-{tag}"
        with open(os.path.join(repo,
                               "kubeflow_tpu/manifests/notebooks.py")) as f:
            content = f.read()
        assert f'JUPYTER_WEB_APP_VERSION = "{tag}"' in content
        assert 'VERSION = "v0.1.0"' in content
        assert git(repo, "log", "-n", "1", "--pretty=%s") == result.pr_title
        assert result.images[0] in result.pr_body

    def test_idempotent_when_pinned(self, repo):
        update_component(repo, "jupyter-web-app")
        # the bot commit itself does not touch the source tree, so the
        # tag is unchanged and a rerun is a no-op
        again = update_component(repo, "jupyter-web-app")
        assert not again.changed
        assert again.branch == ""

    def test_unknown_component(self, repo):
        with pytest.raises(KeyError, match="unknown component"):
            update_component(repo, "nope")

    def test_source_map_paths_and_pins_exist(self):
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        for src, pin, pin_name, image_names in COMPONENT_SOURCES.values():
            assert os.path.exists(os.path.join(repo_root, src)), src
            pin_path = os.path.join(repo_root, pin)
            assert os.path.exists(pin_path), pin
            with open(pin_path) as f:
                content = f.read()
            assert f'{pin_name} = "' in content, pin_name
            # every advertised image is actually tagged by that pin in
            # the manifests module (the PR payload must name images the
            # deployments reference, not the component key)
            for name in image_names:
                assert f"{name}:{{{pin_name}}}" in content, name
