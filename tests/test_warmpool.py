"""Warm-pod pools (ISSUE 9): the scheduler advertises idle hosts,
keeps pre-initialized pods on them, placement prefers adopting them,
and the operator retires the warm pod when the gang lands — so
rebinds/resizes/scale-ups start warm instead of cold. Plus the sim's
per-restart-cost model that makes the sched A/Bs honest about it.
"""

import json

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.api.topology import parse_topology
from kubeflow_tpu.api.trainingjob import BINDING_ANNOTATION
from kubeflow_tpu.cluster.fake import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
from kubeflow_tpu.scheduler import warmpool
from kubeflow_tpu.scheduler.core import SliceScheduler
from kubeflow_tpu.scheduler.inventory import (Placement, PoolState,
                                              SliceInventory, SliceRect)
from kubeflow_tpu.scheduler.queue import SchedulerConfig

pytestmark = pytest.mark.warmstart


def tpujob(name, topo="v5e-8", ns="kubeflow"):
    return {"apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"replicaSpecs": {"TPU": {
                "tpuTopology": topo,
                "template": {"spec": {"containers": [{"name": "c"}]}}}},
                "schedulingPolicy": {"queue": "q", "priority": 1},
                "sharding": {"data": -1}}}


def drive(cluster, mgr, ticks=4):
    for _ in range(ticks):
        mgr.run_pending()
        cluster.tick()
    mgr.run_pending()


@pytest.fixture
def env():
    cluster = FakeCluster()
    cluster.add_tpu_slice_nodes("v5e-32", pool="big")
    mgr = Manager(cluster)
    mgr.add(SliceScheduler(SchedulerConfig(warm_pods=2)))
    mgr.add(TrainingJobReconciler("TPUJob"))
    yield cluster, mgr
    for c in mgr.controllers:
        c.stop()


# ----------------------------------------------------------- wire format


class TestWire:
    def test_placement_warm_hosts_roundtrip(self):
        p = Placement(topology="v5e-8", num_slices=1,
                      slices=[SliceRect("big", 0, 0, 2, 4)],
                      warm_hosts=[{"pool": "big", "host": 1}])
        d = p.to_dict()
        assert d["warmHosts"] == [{"pool": "big", "host": 1}]
        q = Placement.from_dict(d)
        assert q.warm_hosts == [{"pool": "big", "host": 1}]
        # absent/garbage warmHosts degrade to [] — advisory only
        assert Placement.from_dict(
            {"topology": "v5e-8", "slices": []}).warm_hosts == []
        assert Placement.from_dict(
            {"topology": "v5e-8", "slices": [],
             "warmHosts": ["junk", {"pool": "p"}]}).warm_hosts == []

    def test_binding_matches_ignores_warm_hosts(self):
        from kubeflow_tpu.api.trainingjob import TrainingJob
        from kubeflow_tpu.scheduler.queue import binding_matches
        job = TrainingJob.from_manifest(tpujob("j"))
        p = Placement(topology="v5e-8", num_slices=1,
                      slices=[SliceRect("big", 0, 0, 2, 4)],
                      warm_hosts=[{"pool": "big", "host": 0}])
        assert binding_matches(p, job)

    def test_scheduler_config_warm_pods_wire(self):
        assert SchedulerConfig.from_dict({"warmPods": 3}).warm_pods == 3
        assert SchedulerConfig.from_dict({}).warm_pods == 0
        from kubeflow_tpu.manifests.training import tpu_scheduler
        cm = next(o for o in tpu_scheduler(warm_pods=4)
                  if o["kind"] == "ConfigMap")
        cfg = SchedulerConfig.from_dict(
            json.loads(cm["data"]["config.json"]))
        assert cfg.warm_pods == 4


# ------------------------------------------------------- slot mechanics


class TestSlots:
    def _inventory(self):
        return SliceInventory([PoolState("big",
                                         parse_topology("v5e-32"))])

    def test_free_hosts_deterministic_and_occupancy_aware(self):
        inv = self._inventory()
        hosts = warmpool.free_hosts(inv)
        assert hosts == [{"pool": "big", "host": i}
                         for i in range(len(hosts))]
        assert hosts == warmpool.free_hosts(inv)   # stable
        # occupy host 0's cells: it drops out
        from kubeflow_tpu.scheduler import health
        cells = list(health.host_cells("big", inv.pools["big"].topology,
                                       0))
        _p, x, y = cells[0]
        inv.pools["big"].grid[x][y] = "ns/job"
        assert {"pool": "big", "host": 0} not in warmpool.free_hosts(inv)

    def test_write_slots_is_write_on_change(self):
        cluster = FakeCluster()
        # empty slots with no CM: no litter
        warmpool.write_slots(cluster, [])
        assert cluster.get_or_none("v1", "ConfigMap",
                                   warmpool.WARM_POOL_NAMESPACE,
                                   warmpool.SLOTS_CONFIG_MAP) is None
        warmpool.write_slots(cluster, [{"pool": "big", "host": 1}])
        assert warmpool.slots_of(cluster) == [{"pool": "big", "host": 1}]
        warmpool.write_slots(cluster, [])
        assert warmpool.slots_of(cluster) == []

    def test_slots_of_tolerates_garbage(self):
        cluster = FakeCluster()
        cm = k8s.make("v1", "ConfigMap", warmpool.SLOTS_CONFIG_MAP,
                      warmpool.WARM_POOL_NAMESPACE)
        cm["data"] = {warmpool.SLOTS_KEY: "not json"}
        cluster.create(cm)
        assert warmpool.slots_of(cluster) == []

    def test_reconcile_creates_and_retires(self):
        cluster = FakeCluster()
        inv = self._inventory()
        slots = [{"pool": "big", "host": 0}, {"pool": "big", "host": 2}]
        created, deleted = warmpool.reconcile_warm_pods(cluster, slots,
                                                        inv)
        assert (created, deleted) == (2, 0)
        names = {p["metadata"]["name"]
                 for p in warmpool.list_warm_pods(cluster)}
        assert names == {"warm-big-h0", "warm-big-h2"}
        pod = cluster.get("v1", "Pod", warmpool.WARM_POOL_NAMESPACE,
                          "warm-big-h0")
        assert pod["spec"]["nodeSelector"]["kubeflow.org/pool"] == "big"
        # shrink the advertisement: the stale pod retires
        created, deleted = warmpool.reconcile_warm_pods(
            cluster, slots[:1], inv)
        assert (created, deleted) == (0, 1)
        assert {p["metadata"]["name"]
                for p in warmpool.list_warm_pods(cluster)} == \
            {"warm-big-h0"}

    def test_reconcile_keeps_pending_adoption(self):
        """A pod whose slot a live binding names (pending adoption by
        the operator) must NOT be retired by the scheduler's pass —
        the race that would turn every adoption into a cold create."""
        cluster = FakeCluster()
        inv = self._inventory()
        warmpool.reconcile_warm_pods(cluster,
                                     [{"pool": "big", "host": 0}], inv)
        created, deleted = warmpool.reconcile_warm_pods(
            cluster, [], inv, keep={("big", 0)})
        assert (created, deleted) == (0, 0)
        assert warmpool.list_warm_pods(cluster)
        # keep released: the pod retires on the next pass
        _c, deleted = warmpool.reconcile_warm_pods(cluster, [], inv)
        assert deleted == 1


# --------------------------------------------------- placement preference


class TestPreference:
    def test_prefer_tips_equal_fragmentation_ties(self):
        from kubeflow_tpu.scheduler import health
        inv = SliceInventory([PoolState("big",
                                        parse_topology("v5e-32"))])
        pool_topo = inv.pools["big"].topology
        topo = parse_topology("v5e-8")
        baseline = inv.place_gang(topo, 1)
        base_cells = {c for r in baseline.slices for c in r.cells()}
        # a warm slot on a host the un-preferred placement does NOT
        # touch: the preference must move the rect onto it
        prefer = next(
            cells for h in range(pool_topo.num_hosts)
            if not (cells := set(health.host_cells(
                "big", pool_topo, h))) & base_cells)
        preferred = inv.place_gang(topo, 1, prefer=prefer)
        assert preferred is not None
        placed = {c for r in preferred.slices for c in r.cells()}
        assert placed & prefer, "preference did not tip the placement"
        assert baseline.slices != preferred.slices

    def test_prefer_never_beats_fragmentation(self):
        """A warm slot in the MIDDLE of the free region must not pull a
        placement that splits the largest free rectangle."""
        inv = SliceInventory([PoolState("big",
                                        parse_topology("v5e-32"))])
        pool = inv.pools["big"]
        rows, cols = pool.rows, pool.cols
        # occupy the left half except a full-height column strip so the
        # best (fragmentation) cut is unambiguous
        for x in range(rows):
            for y in range(cols // 2):
                pool.grid[x][y] = "ns/other"
        topo = parse_topology("v5e-4")
        base = inv.place_gang(topo, 1)
        # prefer cells dead center of the free half: the chosen rect may
        # move along the tie surface but the fragmentation score must
        # not degrade
        mid = {("big", rows // 2, cols // 2 + 1)}
        placed = inv.place_gang(topo, 1, prefer=mid)
        def frag_after(p):
            for r in p.slices:
                pool.occupy("probe", r)
            s = pool.max_free_rect()
            pool.release("probe")
            return s
        assert frag_after(placed) >= frag_after(base)


# ------------------------------------------------------ control plane e2e


class TestControlPlane:
    def test_scheduler_advertises_and_creates_warm_pods(self, env):
        cluster, mgr = env
        cluster.create(tpujob("j1"))
        drive(cluster, mgr)
        slots = warmpool.slots_of(cluster)
        assert len(slots) == 2
        names = {p["metadata"]["name"]
                 for p in warmpool.list_warm_pods(cluster)}
        assert names == {warmpool.warm_pod_name(s["pool"], s["host"])
                         for s in slots}

    def test_bind_adopts_warm_pod_end_to_end(self, env):
        """THE adoption path: slots advertised after j1, j2's binding
        lands on them (placement preference), records warmHosts, the
        operator retires the warm pods and marks the gang."""
        cluster, mgr = env
        cluster.create(tpujob("j1"))
        drive(cluster, mgr)
        assert warmpool.slots_of(cluster)
        cluster.create(tpujob("j2"))
        drive(cluster, mgr)
        m = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                        "kubeflow", "j2")
        binding = json.loads(k8s.annotations_of(m)[BINDING_ANNOTATION])
        assert binding.get("warmHosts"), "bind did not land on warm slots"
        pods = [p for p in cluster.list("v1", "Pod", "kubeflow")
                if p["metadata"]["name"].startswith("j2-")]
        assert pods
        for pod in pods:
            adopted = json.loads(k8s.annotations_of(pod)[
                warmpool.ADOPTED_ANNOTATION])
            assert adopted == binding["warmHosts"]
            envm = {e["name"]: e["value"]
                    for e in pod["spec"]["containers"][0]["env"]}
            assert envm[warmpool.WARM_START_ENV] == "1"
        # the adopted pods are gone (never two pods on one host)
        live = {p["metadata"]["name"]
                for p in warmpool.list_warm_pods(cluster)}
        for slot in binding["warmHosts"]:
            assert warmpool.warm_pod_name(slot["pool"],
                                          slot["host"]) not in live

    def test_warm_pods_zero_keeps_cluster_clean(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(SliceScheduler(SchedulerConfig(warm_pods=0)))
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob("j1"))
        drive(cluster, mgr)
        assert warmpool.list_warm_pods(cluster) == []
        assert cluster.get_or_none(
            "v1", "ConfigMap", warmpool.WARM_POOL_NAMESPACE,
            warmpool.SLOTS_CONFIG_MAP) is None
        for c in mgr.controllers:
            c.stop()

    def test_knob_turned_off_retires_pool(self, env):
        cluster, mgr = env
        sched = mgr.controllers[0].reconciler \
            if hasattr(mgr.controllers[0], "reconciler") else None
        cluster.create(tpujob("j1"))
        drive(cluster, mgr)
        assert warmpool.list_warm_pods(cluster)
        # flip the deployed knob off via the live ConfigMap (the
        # explicit-config path is pinned, so patch the scheduler's
        # config object directly)
        for c in mgr.controllers:
            r = getattr(c, "reconciler", None)
            if isinstance(r, SliceScheduler):
                r._explicit_config = SchedulerConfig(warm_pods=0)
        del sched
        cluster.create(tpujob("kick"))   # trigger a pass
        drive(cluster, mgr)
        assert warmpool.list_warm_pods(cluster) == []


# ---------------------------------------------------------- sim honesty


class TestSimRestartCosts:
    def test_restart_cost_charges_startup_and_drops_utilization(self):
        from kubeflow_tpu.scheduler.sim import make_workload, simulate
        jobs = make_workload(0, n_jobs=12)
        free = simulate([j for j in jobs], pools=("v5e-32",),
                        policy="preempt")
        jobs = make_workload(0, n_jobs=12)
        costly = simulate([j for j in jobs], pools=("v5e-32",),
                          policy="preempt", restart_ticks=2.0)
        assert free["startup_ticks"] == 0
        assert costly["startup_ticks"] > 0
        assert costly["chip_utilization"] < free["chip_utilization"]
        assert costly["makespan_ticks"] >= free["makespan_ticks"]

    def test_default_zero_cost_reproduces_legacy_numbers(self):
        """restart_ticks=0 must be bit-identical to the pre-warmstart
        sim: every published sched/elastic table stays comparable."""
        from kubeflow_tpu.scheduler.sim import make_workload, simulate
        a = simulate(make_workload(1, n_jobs=12), pools=("v5e-32",),
                     policy="elastic")
        b = simulate(make_workload(1, n_jobs=12), pools=("v5e-32",),
                     policy="elastic", restart_ticks=0.0)
        a.pop("startup_ticks"), b.pop("startup_ticks")
        assert a == b

    def test_compare_restart_costs_orders_arms(self):
        from kubeflow_tpu.scheduler.sim import compare_restart_costs
        table = compare_restart_costs(
            [0, 1], costs={"free": 0, "cold": 2.5, "warm": 0.6,
                           "aot": 0.2},
            n_jobs=12, pools=("v5e-32",))
        for policy in ("preempt", "elastic"):
            t = table[policy]
            assert t["free"]["startup_ticks"] == 0
            assert t["cold"]["startup_ticks"] > \
                t["warm"]["startup_ticks"] > \
                t["aot"]["startup_ticks"] > 0
            # the headline honesty: free restarts overstate utilization
            assert t["free"]["chip_utilization"] >= \
                t["cold"]["chip_utilization"]
            # ...and the warm-start stack buys most of it back
            assert t["aot"]["chip_utilization"] >= \
                t["cold"]["chip_utilization"]
