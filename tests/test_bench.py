"""Bench artifact contracts that must not regress before a TPU session:
the fused-blocks row/winner assembly and the routing-table publish the
measured-routing path consumes (bench.py; KFTPU_FUSED_ROUTING_TABLE in
models/resnet.py). Pure logic — no kernels run here."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # bench.py lives at the repo root

from bench import assemble_block_row, publish_routing_table  # noqa: E402


class TestAssembleBlockRow:
    def test_fused_wins(self):
        row, winner, winner_s = assemble_block_row(
            5, "batch", xla_s=0.010, fused_s=0.008)
        assert winner == "batch" and winner_s == 0.008
        assert row == {"count": 5, "route_model": "batch",
                       "xla_ms": 10.0, "fused_ms": 8.0,
                       "fused_vs_xla": 1.25, "winner": "batch"}

    def test_xla_wins(self):
        row, winner, winner_s = assemble_block_row(
            3, "spatial:14", xla_s=0.010, fused_s=0.021)
        assert winner == "xla" and winner_s == 0.010
        assert row["fused_vs_xla"] == 0.476
        assert row["route_model"] == "spatial:14"

    def test_no_fused_measurement_keeps_xla(self):
        row, winner, winner_s = assemble_block_row(
            2, "xla", xla_s=0.004, fused_s=None)
        assert winner == "xla" and winner_s == 0.004
        assert "fused_ms" not in row and "fused_vs_xla" not in row

    def test_tie_prefers_xla(self):
        # equal times must not flip routing away from the default path
        _, winner, _ = assemble_block_row(1, "batch", 0.01, 0.01)
        assert winner == "xla"


class TestPublishRoutingTable:
    def test_written_table_round_trips_through_fused_route(self, tmp_path,
                                                           monkeypatch):
        """The file the microbench publishes is exactly what
        _fused_route consumes — winner strings included."""
        from kubeflow_tpu.models import resnet as R
        routes = {
            R.geometry_key(7, 7, 2048, 512, 2048): "xla",
            R.geometry_key(14, 14, 1024, 256, 1024): "batch",
            R.geometry_key(56, 56, 256, 64, 256): "spatial:14",
        }
        path = tmp_path / "out" / "routing.json"   # dir does not exist
        publish_routing_table(routes, str(path),
                              {"device_kind": "TPU v5 lite"})
        saved = json.loads(path.read_text())
        assert saved["device_kind"] == "TPU v5 lite"
        monkeypatch.setenv("KFTPU_FUSED_ROUTING_TABLE", str(path))
        assert R._fused_route(7, 7, 2048, 512, 2048) == ("xla", None)
        assert R._fused_route(14, 14, 1024, 256, 1024) == ("batch", None)
        assert R._fused_route(56, 56, 256, 64, 256) == ("spatial", 14)
        # no stray temp file after the atomic publish
        assert sorted(p.name for p in path.parent.iterdir()) == \
            ["routing.json"]

    def test_overwrite_is_atomic_replace(self, tmp_path):
        path = tmp_path / "routing.json"
        publish_routing_table({"a": "xla"}, str(path), {})
        publish_routing_table({"a": "batch"}, str(path), {})
        assert json.loads(path.read_text())["routes"] == {"a": "batch"}


def test_bench_row_winner_strings_match_route_parser(tmp_path, monkeypatch):
    """Every winner string assemble_block_row can emit parses back to a
    route in _fused_route's vocabulary — published through the real
    writer, consumed through the real reader."""
    from kubeflow_tpu.models import resnet as R
    for i, (route_str, expect) in enumerate(
            (("batch", ("batch", None)), ("spatial:4", ("spatial", 4)))):
        _, winner, _ = assemble_block_row(1, route_str, 1.0, 0.5)
        assert winner == route_str
        path = tmp_path / f"routing-{i}.json"
        publish_routing_table({R.geometry_key(1, 1, 1, 1, 1): winner},
                              str(path), {})
        monkeypatch.setenv("KFTPU_FUSED_ROUTING_TABLE", str(path))
        assert R._fused_route(1, 1, 1, 1, 1) == expect
