"""Run FakeCluster-based controller tests over the real HTTP wire.

``make_env_cluster("http")`` wraps a FakeCluster in a ClusterAPIServer and
returns an HttpEnvCluster: every KubeClient call goes client → apiserver →
FakeCluster over real sockets (with sync_watches read-your-writes), while
FakeCluster-only test helpers (tick, fail_pod, add_tpu_slice_nodes, ...)
hit the backend directly followed by a watch catch-up barrier — so the
same deterministic test matrix exercises the wire path end to end
(VERDICT round-1 item 2: "run the whole existing reconciler test matrix
over the HTTP client").
"""

from __future__ import annotations

from kubeflow_tpu.cluster.apiserver import ClusterAPIServer
from kubeflow_tpu.cluster.fake import FakeCluster
from kubeflow_tpu.cluster.http_client import HttpKubeClient

# backend helpers that mutate cluster state outside the client (the test
# driver's hand on the scheduler/kubelet); each needs a catch-up barrier
_HELPER_MUTATORS = {"tick", "schedule", "set_pod_phase", "fail_pod",
                    "add_node", "add_tpu_slice_nodes"}


class HttpEnvCluster(HttpKubeClient):
    def __init__(self, backend: FakeCluster, server: ClusterAPIServer):
        # set before super().__init__ so __getattr__ never recurses
        object.__setattr__(self, "_backend", backend)
        object.__setattr__(self, "_server", server)
        super().__init__(server.url, sync_watches=True)

    def __getattr__(self, name):
        attr = getattr(self._backend, name)
        if name in _HELPER_MUTATORS and callable(attr):
            def wrapped(*a, **kw):
                out = attr(*a, **kw)
                self.wait_caught_up(self._backend._rv_n)
                return out
            return wrapped
        return attr

    def close_env(self) -> None:
        self.close()
        self._server.stop()


def make_env_cluster(mode: str, **fake_kwargs):
    """Returns (cluster, cleanup). mode: "direct" | "http"."""
    backend = FakeCluster(**fake_kwargs)
    if mode == "direct":
        return backend, lambda: None
    server = ClusterAPIServer(backend, port=0)
    server.start()
    proxy = HttpEnvCluster(backend, server)
    return proxy, proxy.close_env
