"""Cold-start elimination (ISSUE 9): the compile cache's contracts, the
AOT executable export/load fallback ladder, and the train()-level warm
start — every rung must degrade to the next, never kill the run, and a
cold-started vs AOT-warm-started resumed run must agree to <=1e-5.

- compile_cache.py direct coverage (the satellite): the gs:// URI branch
  (no bogus local 'gs:' dir), the latched-None reset_cache() path, and
  the broken-volume downgrade-to-warning contract.
- aot.py unit matrix: roundtrip, absent/corrupt file, key mismatch,
  signature mismatch — all fall back to None (test-pinned).
- worker-level drills (compute): export-then-load across processes is
  bench --mode warmstart's job; in-process here we pin start_kind, the
  resumed-run params parity, and the corrupt/missing-volume fallbacks.
"""

import json
import os
import pickle

import pytest

pytestmark = pytest.mark.warmstart


# ------------------------------------------------------- compile cache


class TestCompileCache:
    def _reset_jax_cache_config(self):
        import jax
        jax.config.update("jax_compilation_cache_dir", None)

    def test_gs_uri_branch_creates_no_local_dir(self, tmp_path,
                                                monkeypatch):
        """A bucket URI must reach jax's config untouched and must NOT
        become a bogus local './gs:' directory (the makedirs branch is
        for local paths only — etils.epath handles the bucket)."""
        from kubeflow_tpu.runtime.compile_cache import \
            enable_compilation_cache
        monkeypatch.chdir(tmp_path)
        try:
            out = enable_compilation_cache("gs://bucket/kftpu-cache")
            assert out == "gs://bucket/kftpu-cache"
            assert not (tmp_path / "gs:").exists()
            import jax
            assert jax.config.jax_compilation_cache_dir == \
                "gs://bucket/kftpu-cache"
        finally:
            self._reset_jax_cache_config()

    def test_latched_none_cache_is_reset(self, tmp_path, monkeypatch):
        """A process that compiled before the cache dir was set latched
        a None cache inside jax (_cache_initialized) and would silently
        never persist; enable_compilation_cache must reset the latch."""
        from jax._src import compilation_cache as _cc

        from kubeflow_tpu.runtime.compile_cache import \
            enable_compilation_cache
        calls = []
        monkeypatch.setattr(_cc, "_cache_initialized", True,
                            raising=False)
        monkeypatch.setattr(_cc, "_cache", None, raising=False)
        monkeypatch.setattr(_cc, "reset_cache",
                            lambda: calls.append(1))
        try:
            out = enable_compilation_cache(str(tmp_path / "cache"))
            assert out == str(tmp_path / "cache")
            assert calls, "latched-None cache was not reset"
        finally:
            self._reset_jax_cache_config()

    def test_initialized_cache_is_not_reset(self, tmp_path, monkeypatch):
        """A LIVE cache object must not be torn down by a second call
        (repeated in-process train() is the normal katib/bench case)."""
        from jax._src import compilation_cache as _cc

        from kubeflow_tpu.runtime.compile_cache import \
            enable_compilation_cache
        calls = []
        monkeypatch.setattr(_cc, "_cache_initialized", True,
                            raising=False)
        monkeypatch.setattr(_cc, "_cache", object(), raising=False)
        monkeypatch.setattr(_cc, "reset_cache",
                            lambda: calls.append(1))
        try:
            enable_compilation_cache(str(tmp_path / "cache"))
            assert not calls
        finally:
            self._reset_jax_cache_config()

    def test_broken_volume_downgrades_to_warning(self, tmp_path,
                                                 caplog):
        """A cache path that cannot be a directory (a FILE is in the
        way — the broken-volume case) must return None with a warning,
        never raise: a dead cache volume must not kill a gang."""
        from kubeflow_tpu.runtime.compile_cache import \
            enable_compilation_cache
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("x")
        with caplog.at_level("WARNING",
                             logger="kubeflow_tpu.runtime.compile_cache"):
            out = enable_compilation_cache(str(blocker / "cache"))
        assert out is None
        assert any("compilation cache disabled" in r.message
                   for r in caplog.records)

    def test_unset_env_is_noop(self, monkeypatch):
        from kubeflow_tpu.runtime.compile_cache import (
            COMPILE_CACHE_ENV, enable_compilation_cache)
        monkeypatch.delenv(COMPILE_CACHE_ENV, raising=False)
        assert enable_compilation_cache() is None

    def test_namespace_cache_dir_and_defaults(self):
        from kubeflow_tpu.runtime.aot import default_aot_dir
        from kubeflow_tpu.runtime.compile_cache import (
            default_cache_dir, namespace_cache_dir)
        assert namespace_cache_dir("/mnt/cache/", "team-a") == \
            "/mnt/cache/team-a"
        assert default_cache_dir("/ckpt/") == "/ckpt/.jax-compile-cache"
        assert default_aot_dir("/ckpt") == "/ckpt/.jax-aot-executables"

    def test_compile_stats_derives_backend_compiles(self):
        """xla_backend_compiles = requests - hits: jax's backend-compile
        duration event fires on cache hits too, so the raw event count
        cannot be the no-XLA-observed signal (bench --mode warmstart
        asserts on the derived number)."""
        from kubeflow_tpu.runtime import compile_cache as cc
        s = dict(cc._STATS)
        try:
            cc._STATS["cache_requests"] += 5
            cc._STATS["cache_hits"] += 3
            out = cc.compile_stats()
            assert out["xla_backend_compiles"] == \
                s["cache_requests"] + 5 - (s["cache_hits"] + 3)
        finally:
            cc._STATS.update(s)


# ------------------------------------------------------------- aot unit


@pytest.mark.compute
class TestAotLadder:
    """The serialized-executable rung: every failure mode returns None
    (the caller falls back to cache, then compile) — test-pinned per
    the acceptance criteria."""

    @pytest.fixture(scope="class")
    def compiled(self):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.runtime import aot
        x = jnp.arange(8, dtype=jnp.float32)
        fn = jax.jit(lambda v: v * 2.0)
        comp = fn.lower(x).compile()
        sig = aot.abstract_signature(x)
        return comp, sig, x

    def test_roundtrip(self, tmp_path, compiled):
        import jax.numpy as jnp

        from kubeflow_tpu.runtime import aot
        comp, sig, x = compiled
        key = "k" * 24
        path = aot.export_step(str(tmp_path), key, comp, sig)
        assert path and os.path.exists(path)
        loaded = aot.load_step(str(tmp_path), key, sig)
        assert loaded is not None
        assert jnp.allclose(loaded(x), x * 2.0)

    def test_absent_file_is_a_miss(self, tmp_path, compiled):
        from kubeflow_tpu.runtime import aot
        _comp, sig, _x = compiled
        assert aot.load_step(str(tmp_path), "nope" * 6, sig) is None

    def test_corrupt_file_falls_back(self, tmp_path, compiled):
        from kubeflow_tpu.runtime import aot
        comp, sig, _x = compiled
        key = "c" * 24
        path = aot.export_step(str(tmp_path), key, comp, sig)
        with open(path, "wb") as f:
            f.write(b"\x00garbage, not a pickle")
        assert aot.load_step(str(tmp_path), key, sig) is None

    def test_truncated_file_falls_back(self, tmp_path, compiled):
        from kubeflow_tpu.runtime import aot
        comp, sig, _x = compiled
        key = "t" * 24
        path = aot.export_step(str(tmp_path), key, comp, sig)
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[:len(blob) // 2])
        assert aot.load_step(str(tmp_path), key, sig) is None

    def test_key_mismatch_falls_back(self, tmp_path, compiled):
        """A record written under key A hand-copied to key B's path (or
        a filename collision) is detected by the embedded key."""
        from kubeflow_tpu.runtime import aot
        comp, sig, _x = compiled
        key_a, key_b = "a" * 24, "b" * 24
        aot.export_step(str(tmp_path), key_a, comp, sig)
        os.rename(aot._path(str(tmp_path), key_a),
                  aot._path(str(tmp_path), key_b))
        assert aot.load_step(str(tmp_path), key_b, sig) is None

    def test_signature_mismatch_falls_back(self, tmp_path, compiled):
        import jax.numpy as jnp

        from kubeflow_tpu.runtime import aot
        comp, sig, _x = compiled
        key = "s" * 24
        aot.export_step(str(tmp_path), key, comp, sig)
        other = aot.abstract_signature(
            jnp.zeros((4, 4), jnp.bfloat16))
        assert aot.load_step(str(tmp_path), key, other) is None

    def test_export_failure_downgrades(self, tmp_path, compiled):
        """An unwritable AOT dir (file in the way) must warn, not
        raise — export is an optimization."""
        from kubeflow_tpu.runtime import aot
        comp, sig, _x = compiled
        blocker = tmp_path / "blocked"
        blocker.write_text("x")
        assert aot.export_step(str(blocker / "aot"), "e" * 24,
                               comp, sig) is None

    def test_atomic_export_leaves_no_tmp(self, tmp_path, compiled):
        from kubeflow_tpu.runtime import aot
        comp, sig, _x = compiled
        aot.export_step(str(tmp_path), "f" * 24, comp, sig)
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]

    def test_record_carries_key_and_signature(self, tmp_path, compiled):
        from kubeflow_tpu.runtime import aot
        comp, sig, _x = compiled
        key = "r" * 24
        path = aot.export_step(str(tmp_path), key, comp, sig)
        with open(path, "rb") as f:
            record = pickle.load(f)
        assert record["key"] == key
        assert record["signature"] == sig


class TestStepKey:
    def test_deterministic_and_sensitive(self):
        from kubeflow_tpu.runtime import aot
        base = dict(topology="v5e-8", num_slices=1,
                    model_fingerprint="m1", weight_update="replicated",
                    sharding={"data": 8}, global_batch=64)
        k1 = aot.step_key(**base)
        assert k1 == aot.step_key(**base)
        assert len(k1) == 24
        # every key component must rotate the key
        for delta in (dict(topology="v5e-16"), dict(num_slices=2),
                      dict(model_fingerprint="m2"),
                      dict(weight_update="sharded"),
                      dict(sharding={"data": 4, "tensor": 2}),
                      dict(global_batch=128)):
            assert aot.step_key(**{**base, **delta}) != k1, delta

    def test_recipe_fingerprint_stable_and_sensitive(self):
        from kubeflow_tpu.runtime.recipe import recipe_fingerprint
        a = recipe_fingerprint(workload="transformer", lr=0.1, steps=10)
        assert a == recipe_fingerprint(workload="transformer", lr=0.1,
                                       steps=10)
        assert a != recipe_fingerprint(workload="transformer", lr=0.2,
                                       steps=10)
        # non-JSON values degrade to repr, not an error
        assert recipe_fingerprint(obj=object) != a


@pytest.mark.katib
class TestCompileShapeFingerprint:
    """ISSUE 19 over-keying fix: tuned scalars (lr/warmup/steps) are
    runtime INPUTS under the runtime schedule, so they must drop out of
    the compile-shape key — while anything that changes the program
    still rotates it, and the full recipe_fingerprint stays scalar-
    sensitive (it is trial identity, not a cache key)."""

    BASE = dict(workload="transformer", optimizer="adam",
                lr_schedule="cosine", learning_rate=0.1,
                warmup_steps=5, steps=100, global_batch=64)

    def test_runtime_constants_drop_out_of_shape_key(self):
        from kubeflow_tpu.runtime.recipe import compile_shape_fingerprint
        k = compile_shape_fingerprint(**self.BASE)
        # lr-variant trials: same shape key — the whole warm-start story
        for delta in (dict(learning_rate=0.9), dict(warmup_steps=500),
                      dict(steps=7000),
                      dict(learning_rate=0.3, warmup_steps=0, steps=42)):
            assert compile_shape_fingerprint(**{**self.BASE, **delta}) \
                == k, delta

    def test_program_changes_still_rotate_the_shape_key(self):
        from kubeflow_tpu.runtime.recipe import compile_shape_fingerprint
        k = compile_shape_fingerprint(**self.BASE)
        for delta in (dict(workload="resnet50"), dict(optimizer="sgd"),
                      dict(lr_schedule="linear"),
                      dict(global_batch=128)):
            assert compile_shape_fingerprint(**{**self.BASE, **delta}) \
                != k, delta

    def test_runtime_constants_key_captures_the_scalars(self):
        from kubeflow_tpu.runtime.recipe import (runtime_constants_key,
                                                 split_recipe_knobs)
        a = runtime_constants_key(**self.BASE)
        assert a == runtime_constants_key(**self.BASE)
        assert a != runtime_constants_key(
            **{**self.BASE, "learning_rate": 0.9})
        # shape-only change leaves the runtime key alone
        assert a == runtime_constants_key(
            **{**self.BASE, "workload": "resnet50"})
        shape, runtime = split_recipe_knobs(dict(self.BASE))
        assert set(runtime) == {"learning_rate", "warmup_steps", "steps"}
        assert "global_batch" in shape and "learning_rate" not in shape

    def test_full_fingerprint_remains_scalar_sensitive(self):
        """The split must NOT weaken recipe_fingerprint — it stays the
        trial-identity hash, sensitive to every knob."""
        from kubeflow_tpu.runtime.recipe import (compile_shape_fingerprint,
                                                 recipe_fingerprint)
        a = recipe_fingerprint(**self.BASE)
        b = recipe_fingerprint(**{**self.BASE, "learning_rate": 0.9})
        assert a != b
        assert compile_shape_fingerprint(**self.BASE) == \
            compile_shape_fingerprint(**{**self.BASE,
                                         "learning_rate": 0.9})


# ------------------------------------------------- worker-level drills


def _final_loss(result):
    return float(result.final_metrics.get("loss", float("nan")))


@pytest.mark.compute
class TestWorkerWarmStart:
    KW = dict(workload="transformer", global_batch=8, sync_every=2,
              workload_kwargs={}, seed=0)

    def test_cold_vs_aot_resumed_parity(self, tmp_path, monkeypatch):
        """THE acceptance drill: params parity <=1e-5 between a
        cold-started straight-through run and an AOT-warm-started
        RESUMED run (the rebind shape: same spec, executable exported
        at first bind, loaded on the re-bind). Note the AOT key
        deliberately includes total steps — LR-schedule constants are
        baked into the program — so the export comes from a run of the
        SAME spec, exactly as a real gang restart would see it."""
        import jax
        import numpy as np

        from kubeflow_tpu.cluster.chaos import final_params
        from kubeflow_tpu.runtime.worker import train
        monkeypatch.setenv("KFTPU_COMPILE_CACHE_MIN_SECS", "0")
        aot_dir = str(tmp_path / "aot")
        ck_ref = str(tmp_path / "ck-ref")
        ck_seg = str(tmp_path / "ck-seg")
        ck_aot = str(tmp_path / "ck-aot")

        # the cold-started reference run; its first bind exports the
        # steps=6 executable (the key a rebind of this spec reuses)
        r_ref = train(steps=6, checkpoint_dir=ck_ref,
                      checkpoint_every=3, aot=True, aot_dir=aot_dir,
                      **self.KW)
        assert r_ref.start_kind == "cold"
        assert os.listdir(aot_dir), "first bind exported no executable"
        # an interrupted first half of the same run (the preempted gang)
        train(steps=3, checkpoint_dir=ck_seg, checkpoint_every=3,
              **self.KW)
        # the rebind: same spec, resumeFrom the forced checkpoint, AOT
        # executable loaded — no XLA for the step
        r_aot = train(steps=6, checkpoint_dir=ck_aot,
                      checkpoint_every=3, resume_from=ck_seg,
                      aot=True, aot_dir=aot_dir, **self.KW)
        assert r_aot.start_kind == "aot"
        assert r_aot.steps == 3   # resumed at 3, ran 3..6
        pa, pb = final_params(ck_aot), final_params(ck_ref)
        delta = max(jax.tree.leaves(jax.tree.map(
            lambda a, b: float(np.max(np.abs(
                np.asarray(a) - np.asarray(b)))), pa, pb)),
            default=0.0)
        assert delta <= 1e-5, f"cold vs aot-resumed params delta {delta}"
        assert _final_loss(r_aot) == pytest.approx(_final_loss(r_ref),
                                                   abs=1e-5)

    def test_corrupt_executable_falls_back_and_trains(self, tmp_path,
                                                      monkeypatch):
        from kubeflow_tpu.runtime.worker import train
        monkeypatch.setenv("KFTPU_COMPILE_CACHE_MIN_SECS", "0")
        aot_dir = tmp_path / "aot"
        train(steps=2, aot=True, aot_dir=str(aot_dir), **self.KW)
        files = list(aot_dir.iterdir())
        assert files
        files[0].write_bytes(b"corrupt")
        r = train(steps=2, aot=True, aot_dir=str(aot_dir), **self.KW)
        assert r.steps == 2
        assert r.start_kind != "aot"

    def test_key_mismatch_falls_back_and_trains(self, tmp_path,
                                                monkeypatch):
        """A different global batch rotates the key: the old executable
        must be IGNORED (not crash the gang), and the run completes on
        the compile path."""
        from kubeflow_tpu.runtime.worker import train
        monkeypatch.setenv("KFTPU_COMPILE_CACHE_MIN_SECS", "0")
        aot_dir = str(tmp_path / "aot")
        train(steps=2, aot=True, aot_dir=aot_dir, **self.KW)
        kw = dict(self.KW, global_batch=16)
        r = train(steps=2, aot=True, aot_dir=aot_dir, **kw)
        assert r.steps == 2
        assert r.start_kind != "aot"

    def test_missing_cache_volume_never_kills_the_run(self, tmp_path,
                                                      monkeypatch):
        """Both warm-start dirs pointed at an impossible path (a file in
        the way): the run must complete cold."""
        from kubeflow_tpu.runtime.worker import train
        blocker = tmp_path / "file"
        blocker.write_text("x")
        monkeypatch.setenv("KFTPU_COMPILE_CACHE_DIR",
                           str(blocker / "cache"))
        r = train(steps=2, aot=True, aot_dir=str(blocker / "aot"),
                  **self.KW)
        assert r.steps == 2
        assert r.start_kind == "cold"

    def test_aot_without_dir_degrades_with_warning(self, caplog):
        from kubeflow_tpu.runtime.worker import train
        with caplog.at_level("WARNING"):
            r = train(steps=2, aot=True, **self.KW)
        assert r.steps == 2
        assert any("no --aot-dir" in rec.message
                   for rec in caplog.records)

    def test_lr_variant_trials_share_one_executable(self, tmp_path,
                                                    monkeypatch):
        """THE katib warm-start regression (ISSUE 19): two trials that
        differ only in tuned scalars (lr, total steps) under the runtime
        schedule hit the SAME AOT executable — trial 2 starts 'aot' off
        trial 1's export, and the AOT dir holds exactly one record."""
        from kubeflow_tpu.runtime.worker import train
        monkeypatch.setenv("KFTPU_COMPILE_CACHE_MIN_SECS", "0")
        aot_dir = tmp_path / "aot"
        r1 = train(steps=4, learning_rate=0.1, lr_schedule="cosine",
                   runtime_schedule=True, aot=True, aot_dir=str(aot_dir),
                   **self.KW)
        assert r1.start_kind == "cold"
        assert len(list(aot_dir.iterdir())) == 1
        r2 = train(steps=6, learning_rate=0.37, lr_schedule="cosine",
                   runtime_schedule=True, aot=True, aot_dir=str(aot_dir),
                   **self.KW)
        assert r2.start_kind == "aot", \
            "lr-variant trial recompiled: fingerprint is over-keyed"
        assert len(list(aot_dir.iterdir())) == 1, \
            "lr-variant trial exported a second executable"

    def test_changed_model_shape_still_misses(self, tmp_path,
                                              monkeypatch):
        """The split must not UNDER-key: a different global batch (a
        real program change) must miss trial 1's executable and export
        its own."""
        from kubeflow_tpu.runtime.worker import train
        monkeypatch.setenv("KFTPU_COMPILE_CACHE_MIN_SECS", "0")
        aot_dir = tmp_path / "aot"
        train(steps=4, learning_rate=0.1, runtime_schedule=True,
              aot=True, aot_dir=str(aot_dir), **self.KW)
        kw = dict(self.KW, global_batch=16)
        r = train(steps=4, learning_rate=0.1, runtime_schedule=True,
                  aot=True, aot_dir=str(aot_dir), **kw)
        assert r.start_kind != "aot"
        assert len(list(aot_dir.iterdir())) == 2

    def test_runtime_schedule_never_aliases_baked_executables(
            self, tmp_path, monkeypatch):
        """A baked-schedule run and a runtime-schedule run of the same
        spec are DIFFERENT programs: the flag joins the key, so neither
        can load the other's executable."""
        from kubeflow_tpu.runtime.worker import train
        monkeypatch.setenv("KFTPU_COMPILE_CACHE_MIN_SECS", "0")
        aot_dir = tmp_path / "aot"
        train(steps=4, learning_rate=0.1, aot=True,
              aot_dir=str(aot_dir), **self.KW)
        r = train(steps=4, learning_rate=0.1, runtime_schedule=True,
                  aot=True, aot_dir=str(aot_dir), **self.KW)
        assert r.start_kind != "aot"
        assert len(list(aot_dir.iterdir())) == 2

    def test_runtime_schedule_parity_with_baked(self, monkeypatch):
        """Feeding lr through optimizer state must train IDENTICALLY to
        baking it into the program (the schedule math is mirrored in
        runtime/recipe.py _runtime_lr_at)."""
        from kubeflow_tpu.runtime.worker import train
        kw = dict(self.KW, steps=6, learning_rate=0.2,
                  lr_schedule="cosine", warmup_steps=2)
        r_baked = train(**kw)
        r_rt = train(runtime_schedule=True, **kw)
        assert _final_loss(r_rt) == pytest.approx(_final_loss(r_baked),
                                                  abs=1e-5)

    def test_first_step_metric_and_span(self, tmp_path, monkeypatch):
        """The worker emits kftpu_time_to_first_step_seconds labeled by
        start kind plus a first-step span event (the satellite)."""
        from kubeflow_tpu.obs import registry as obsreg
        from kubeflow_tpu.runtime.worker import train
        obsreg.reset_default_registry()
        span_path = str(tmp_path / "spans.jsonl")
        try:
            r = train(steps=2, span_path=span_path, **self.KW)
            assert r.time_to_first_step_s > 0
            text = obsreg.default_registry().render()
            assert "kftpu_time_to_first_step_seconds" in text
            assert f'start="{r.start_kind}"' in text
            events = [json.loads(line)
                      for line in open(span_path) if line.strip()]
            first = [e for e in events
                     if e.get("name") == "first-step"]
            assert first and \
                first[0]["attrs"]["start_kind"] == r.start_kind
            assert first[0]["attrs"]["seconds"] > 0
        finally:
            obsreg.reset_default_registry()
