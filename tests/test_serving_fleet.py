"""Serving fleet-resilience tests (ISSUE 12): circuit-breaker state
machine (evidence decay, trip, half-open probation, manual eject),
health-routed picks, deadline-budgeted failover retries, Retry-After,
tail hedging, graceful drain (server, batcher, gRPC), the client retry
contract, fleet ledgers/metrics/rollup, and the serving manifest's
probe/preStop/PDB plumbing."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.cluster.chaos import ChaosServable, ServingReplicaHarness
from kubeflow_tpu.obs import goodput as gp
from kubeflow_tpu.obs.registry import Registry
from kubeflow_tpu.obs.trace import load_spans
from kubeflow_tpu.serving.fleet import (BREAKER_CLOSED, BREAKER_HALF_OPEN,
                                        BREAKER_OPEN, BreakerConfig,
                                        CircuitBreaker, DeadlineExceededError,
                                        FleetConfig, FleetRouter,
                                        NoReplicaAvailableError,
                                        RequestRejectedError)
from kubeflow_tpu.serving.request_trace import (DEADLINE_HEADER,
                                                REQUEST_ID_HEADER)

pytestmark = pytest.mark.serving_fleet

BODY = json.dumps({"instances": [[1.0, 2.0, 3.0]]}).encode()


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------- circuit breaker


class TestCircuitBreaker:
    def cfg(self, **kw):
        base = dict(half_life_s=10.0, trip_threshold=3.0,
                    release_threshold=1.0, open_s=5.0, open_max_s=60.0,
                    probe_successes=2)
        base.update(kw)
        return BreakerConfig(**base)

    def test_trips_at_threshold_and_decays(self):
        clk = FakeClock()
        b = CircuitBreaker(self.cfg(), clock=clk)
        assert b.state() == BREAKER_CLOSED
        b.record_failure("5xx")            # weight 0.5
        b.record_failure("timeout")        # weight 1.0
        assert b.state() == BREAKER_CLOSED
        tripped = b.record_failure("connect-failure")  # 2.5 < 3 → no
        assert not tripped and b.state() == BREAKER_CLOSED
        assert b.record_failure("timeout")             # 3.5 → trip
        assert b.state() == BREAKER_OPEN
        # decay is the forgiveness: the same evidence long ago scores ~0
        clk.advance(100.0)
        assert b.score() < 0.01

    def test_half_open_probe_then_close_needs_decay_and_successes(self):
        clk = FakeClock()
        b = CircuitBreaker(self.cfg(half_life_s=5.0), clock=clk)
        for _ in range(3):
            b.record_failure("timeout")
        assert b.state() == BREAKER_OPEN
        assert not b.allow_request()       # open: nothing routes
        clk.advance(5.1)                   # cooldown elapsed
        assert b.state() == BREAKER_HALF_OPEN
        # one probe at a time — the second claim loses
        assert b.try_probe()
        assert not b.try_probe()
        clk.advance(10.0)                  # score decays under release
        assert not b.record_success()      # 1/2 probes
        assert b.try_probe()
        assert b.record_success()          # 2/2 AND decayed → closed
        assert b.state() == BREAKER_CLOSED

    def test_probe_failure_reopens_with_extended_cooldown(self):
        clk = FakeClock()
        b = CircuitBreaker(self.cfg(open_s=5.0), clock=clk)
        for _ in range(3):
            b.record_failure("timeout")
        clk.advance(5.1)
        assert b.state() == BREAKER_HALF_OPEN
        assert b.try_probe()
        assert b.record_failure("timeout")  # probe failed → re-open
        assert b.state() == BREAKER_OPEN
        clk.advance(5.1)                    # old cooldown is NOT enough
        assert b.state() == BREAKER_OPEN
        clk.advance(5.0)                    # doubled cooldown elapses
        assert b.state() == BREAKER_HALF_OPEN

    def test_success_without_decay_keeps_half_open(self):
        clk = FakeClock()
        b = CircuitBreaker(self.cfg(half_life_s=1000.0), clock=clk)
        for _ in range(4):
            b.record_failure("timeout")
        clk.advance(5.1)
        assert b.state() == BREAKER_HALF_OPEN
        for _ in range(3):
            assert b.try_probe()
            assert not b.record_success()   # score still hot
        assert b.state() == BREAKER_HALF_OPEN

    def test_manual_eject_never_auto_releases(self):
        clk = FakeClock()
        b = CircuitBreaker(self.cfg(), clock=clk)
        b.eject(manual=True)
        clk.advance(10_000.0)
        assert b.state() == BREAKER_OPEN    # no half-open, ever
        assert not b.allow_request()
        b.release()                         # the human's explicit call
        assert b.state() == BREAKER_CLOSED

    def test_release_probe_frees_an_abandoned_slot(self):
        clk = FakeClock()
        b = CircuitBreaker(self.cfg(open_s=1.0), clock=clk)
        for _ in range(3):
            b.record_failure("timeout")
        clk.advance(1.1)
        assert b.try_probe()
        assert not b.try_probe()    # slot held
        b.release_probe()           # abandoned-hedge path
        assert b.try_probe()        # probe-able again, no evidence

    def test_config_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown breaker config"):
            BreakerConfig.from_dict({"tripThreshold": 2, "typo": 1})
        cfg = BreakerConfig.from_dict({"tripThreshold": 2.5})
        assert cfg.trip_threshold == 2.5
        assert BreakerConfig.from_dict(cfg.to_dict()) == cfg


# ---------------------------------------------------------- fleet ledger


class TestFleetLedger:
    def test_partition_and_sum_check(self):
        led = gp.decompose_fleet_request(1.0, 0.7, 0.2,
                                         hedge_waste_seconds=0.5)
        assert led["badputSeconds"][gp.SERVING_RETRY] == 0.2
        assert led["badputSeconds"][gp.BADPUT_OTHER] == \
            pytest.approx(0.1)
        # hedge_waste overlaps the winner: named, outside the partition
        assert led["badputSeconds"][gp.SERVING_HEDGE_WASTE] == 0.5
        assert gp.fleet_sum_ok(led)
        assert set(led["badputSeconds"]) == \
            set(gp.FLEET_BADPUT_CATEGORIES)

    def test_sum_check_catches_a_leak(self):
        led = gp.decompose_fleet_request(1.0, 0.7, 0.2)
        led["badputSeconds"][gp.BADPUT_OTHER] = 0.0  # silently absorbed
        assert not gp.fleet_sum_ok(led)

    def test_rollup_folds_fleet_spans(self, tmp_path):
        sink = str(tmp_path / "f.jsonl")
        from kubeflow_tpu.obs.trace import SpanWriter
        w = SpanWriter(sink, "fleet")
        for i, (outcome, retries) in enumerate(
                [("ok", 0), ("ok", 2), ("deadline", 3)]):
            w.emit(gp.FLEET_REQUEST_SPAN, start=float(i), end=i + 0.01,
                   trace_id=f"r{i}", outcome=outcome, replica="a",
                   attempts=retries + 1, retries=retries, hedged=i == 1,
                   ledger=gp.decompose_fleet_request(
                       0.01, 0.008, 0.001, 0.002 if i == 1 else 0.0))
        w.close()
        roll = gp.fleet_rollup(sink)
        assert roll["requests"] == 3
        assert roll["outcomes"] == {"ok": 2, "deadline": 1}
        assert roll["retries"] == 5 and roll["hedged"] == 1
        assert roll["badputSeconds"][gp.SERVING_HEDGE_WASTE] == \
            pytest.approx(0.002)
        assert roll["replicas"] == {"a": 3}


# ------------------------------------------------------------- the pick


def _router(urls, clock=None, **cfg_kw):
    cfg = FleetConfig(poll_interval_s=0.05, poll_timeout_s=1.0,
                      backoff_s=0.01, **cfg_kw)
    kw = {"clock": clock} if clock is not None else {}
    return FleetRouter(replicas=urls, config=cfg, **kw)


class TestPick:
    def test_least_loaded_by_queue_depth_and_p99(self):
        router = _router({})
        router.add_replica("busy", "http://127.0.0.1:1")
        router.add_replica("idle", "http://127.0.0.1:2")
        busy, idle = router.replica("busy"), router.replica("idle")
        for rep, depth, p99 in ((busy, 8, 50.0), (idle, 0, 5.0)):
            rep.poll_ok = True
            rep.health = {"models": [{"model": "m", "queueDepth": depth,
                                      "inFlight": 0, "p99Ms": p99}]}
        assert router.pick("m").name == "idle"
        # queue drains on busy, p99 dominates the other way
        busy.health["models"][0]["queueDepth"] = 0
        busy.health["models"][0]["p99Ms"] = 500.0
        assert router.pick("m").name == "idle"
        router.close()

    def test_skips_draining_excluded_and_open(self):
        router = _router({})
        for name in ("a", "b", "c", "d"):
            router.add_replica(name, f"http://127.0.0.1:{ord(name)}")
        router.replica("a").draining = True
        router.replica("b").breaker.eject()
        picked = {router.pick("m", exclude={"c"}).name
                  for _ in range(5)}
        assert picked == {"d"}
        with pytest.raises(NoReplicaAvailableError):
            router.pick("m", exclude={"c", "d"})
        router.close()

    def test_half_open_probe_takes_priority_once(self):
        clk = FakeClock()
        router = _router({}, clock=clk,
                         )
        router.breaker_config = BreakerConfig(open_s=1.0)
        router.add_replica("p", "http://127.0.0.1:1")
        router.add_replica("q", "http://127.0.0.1:2")
        rep = router.replica("p")
        rep.breaker.cfg = router.breaker_config
        for _ in range(3):
            rep.breaker.record_failure("timeout")
        clk.advance(1.1)
        # first pick is the probe; while it is in flight the rest of
        # the traffic routes to the healthy replica
        assert router.pick("m").name == "p"
        assert router.pick("m").name == "q"
        router.close()


# ------------------------------------------- live fleet: retries, drain


@pytest.fixture
def harness_pair(tmp_path):
    sink = str(tmp_path / "spans.jsonl")
    hs = []
    for i in range(2):
        h = ServingReplicaHarness(f"r{i}", span_path=sink,
                                  predict_s=0.001, seed=i)
        h.start()
        hs.append(h)
    yield hs, sink
    for h in hs:
        h.stop()


class TestFailover:
    def test_connect_failure_reroutes_to_different_replica(
            self, harness_pair):
        hs, sink = harness_pair
        router = FleetRouter(
            replicas={hs[0].name: hs[0].url, hs[1].name: hs[1].url},
            config=FleetConfig(max_retries=2, backoff_s=0.01,
                               attempt_timeout_s=1.0,
                               default_deadline_s=5.0),
            span_path=sink)
        try:
            hs[0].kill()
            # every request succeeds; the dead replica's attempts fold
            # into its breaker until it ejects
            for i in range(8):
                out = router.request("chaos", BODY,
                                     request_id=f"fo{i}")
                assert "predictions" in out
            spans = [s for s in load_spans(sink)
                     if s.get("name") == gp.FLEET_REQUEST_SPAN]
            assert all((s["attrs"]["outcome"] == "ok") for s in spans)
            retried = [s for s in spans if s["attrs"]["retries"] > 0]
            assert retried, "the dead replica must have cost retries"
            for s in retried:
                assert gp.fleet_sum_ok(s["attrs"]["ledger"])
                assert s["attrs"]["ledger"]["badputSeconds"][
                    gp.SERVING_RETRY] > 0
        finally:
            router.close()

    def test_5xx_burst_retries_and_4xx_surfaces(self, harness_pair):
        hs, sink = harness_pair
        router = FleetRouter(
            replicas={hs[0].name: hs[0].url, hs[1].name: hs[1].url},
            config=FleetConfig(max_retries=2, backoff_s=0.01,
                               attempt_timeout_s=1.0,
                               default_deadline_s=5.0))
        try:
            hs[0].servable.fail_next(1, status=500)
            hs[1].servable.fail_next(1, status=500)
            out = router.request("chaos", BODY)
            assert "predictions" in out
            # 4xx is meaning: unknown model → 404, never retried
            t0 = time.monotonic()
            with pytest.raises(RequestRejectedError):
                router.request("nosuchmodel", BODY)
            assert time.monotonic() - t0 < 1.0  # no backoff burned
        finally:
            router.close()

    def test_deadline_budget_bounds_retries(self, harness_pair):
        hs, _ = harness_pair
        router = FleetRouter(
            replicas={hs[0].name: hs[0].url, hs[1].name: hs[1].url},
            config=FleetConfig(max_retries=50, backoff_s=0.05,
                               attempt_timeout_s=0.2,
                               default_deadline_s=0.4))
        try:
            for h in hs:
                h.kill()
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceededError):
                router.request("chaos", BODY)
            # the budget, not the huge retry count, ended it
            assert time.monotonic() - t0 < 2.0
        finally:
            router.close()

    def test_deadline_header_bounds_the_server_side_wait(
            self, harness_pair):
        # the ModelServer bounds its batcher wait by the inbound
        # x-request-deadline: an expired budget answers 504 instead of
        # computing for a client that already left
        hs, _ = harness_pair
        req = urllib.request.Request(
            f"{hs[0].url}/v1/models/chaos:predict", data=BODY,
            method="POST",
            headers={"Content-Type": "application/json",
                     REQUEST_ID_HEADER: "dl1",
                     DEADLINE_HEADER: "0.0001"})
        hs[0].servable.wedge()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5.0)
            assert err.value.code == 504
            assert err.value.headers.get(REQUEST_ID_HEADER) == "dl1"
            err.value.read()
        finally:
            hs[0].servable.unwedge()

    def test_retry_after_is_honored(self):
        from http.server import BaseHTTPRequestHandler, HTTPServer
        hits = []

        class Stub(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                hits.append(time.monotonic())
                if len(hits) == 1:
                    # a throttling 503 telling us when to come back
                    body = b'{"error": "throttled"}'
                    self.send_response(503)
                    self.send_header("Retry-After", "0.15")
                else:
                    body = b'{"predictions": [[1.0]]}'
                    self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = HTTPServer(("127.0.0.1", 0), Stub)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        router = FleetRouter(
            replicas={"only": f"http://127.0.0.1:"
                              f"{httpd.server_address[1]}"},
            config=FleetConfig(max_retries=2, backoff_s=0.001,
                               attempt_timeout_s=1.0,
                               default_deadline_s=5.0))
        try:
            out = router.request("m", BODY)
            assert out == {"predictions": [[1.0]]}
            # the server-sent Retry-After (0.15 s) outranks the
            # router's own ~1 ms jittered backoff
            assert hits[1] - hits[0] >= 0.15
        finally:
            router.close()
            httpd.shutdown()
            httpd.server_close()


class TestHedging:
    def test_hedge_saves_the_tail_and_ledgers_waste(self, tmp_path):
        sink = str(tmp_path / "hedge.jsonl")
        slow = ServingReplicaHarness("slow", span_path=sink,
                                     predict_s=0.25)
        fast = ServingReplicaHarness("fast", span_path=sink,
                                     predict_s=0.002)
        slow.start()
        fast.start()
        router = FleetRouter(
            replicas={"slow": slow.url, "fast": fast.url},
            config=FleetConfig(hedge=True, hedge_delay_ms=20.0,
                               attempt_timeout_s=2.0,
                               default_deadline_s=5.0),
            span_path=sink)
        try:
            # force the pick onto the slow replica so the hedge must
            # rescue it
            router.replica("fast").poll_ok = True
            router.replica("fast").health = {
                "models": [{"model": "chaos", "queueDepth": 99,
                            "inFlight": 0, "p99Ms": 0.0}]}
            t0 = time.monotonic()
            out = router.request("chaos", BODY, request_id="hedge1")
            elapsed = time.monotonic() - t0
            assert "predictions" in out
            assert elapsed < 0.2, \
                f"hedge should beat the 250ms primary ({elapsed:.3f}s)"
            span = [s for s in load_spans(sink)
                    if s.get("name") == gp.FLEET_REQUEST_SPAN][-1]
            assert span["attrs"]["hedged"] is True
            # the win is credited to the replica that ANSWERED (the
            # twin), not the slow primary that was hedged around
            assert span["attrs"]["replica"] == "fast"
            assert span["attrs"]["ledger"]["badputSeconds"][
                gp.SERVING_HEDGE_WASTE] > 0
            assert gp.fleet_sum_ok(span["attrs"]["ledger"])
            hedge_events = [s for s in load_spans(sink)
                            if s.get("name") == "fleet-hedge"]
            assert hedge_events and \
                hedge_events[-1]["trace_id"] == "hedge1"
        finally:
            router.close()
            slow.stop()
            fast.stop()


# --------------------------------------------------------------- drain


class TestDrain:
    def test_server_drain_flips_readiness_and_advertises(self):
        h = ServingReplicaHarness("d0", predict_s=0.001)
        h.start()
        try:
            # pre-drain: ready
            with urllib.request.urlopen(f"{h.url}/healthz",
                                        timeout=5) as r:
                assert r.status == 200
            report = h.server.drain(timeout_s=1.0)
            assert report["inFlightRemaining"] == 0
            # readiness flips 503; liveness stays 200; verbose carries
            # draining + uptime (the fleet-router contract)
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{h.url}/healthz", timeout=5)
            assert err.value.code == 503
            err.value.read()
            with urllib.request.urlopen(f"{h.url}/healthz?live=1",
                                        timeout=5) as r:
                assert r.status == 200
            with urllib.request.urlopen(f"{h.url}/healthz?verbose=1",
                                        timeout=5) as r:
                snap = json.loads(r.read())
            assert snap["draining"] is True
            assert snap["uptimeSeconds"] >= 0
            # new predict work is refused with a retryable 503
            req = urllib.request.Request(
                f"{h.url}/v1/models/chaos:predict", data=BODY,
                method="POST",
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5)
            assert err.value.code == 503
            assert err.value.headers.get("Retry-After") is not None
            err.value.read()
        finally:
            h.stop()

    def test_request_racing_a_drain_gets_retryable_503_not_400(self):
        # a request past the handler's draining check that hits the
        # already-draining batcher must read as weather (503 → the
        # fleet re-routes), never as a hard 400
        h = ServingReplicaHarness("d2", predict_s=0.001)
        h.start()
        try:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"{h.url}/v1/models/chaos:predict", data=BODY,
                    method="POST",
                    headers={"Content-Type": "application/json"}),
                timeout=5).read()
            h.server.batcher("chaos").drain(timeout_s=1.0)
            req = urllib.request.Request(
                f"{h.url}/v1/models/chaos:predict", data=BODY,
                method="POST",
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5)
            assert err.value.code == 503
            err.value.read()
        finally:
            h.stop()

    def test_drain_endpoint_is_the_prestop_hook(self):
        h = ServingReplicaHarness("d1", predict_s=0.001)
        h.start()
        try:
            with urllib.request.urlopen(f"{h.url}/drain",
                                        timeout=10) as r:
                report = json.loads(r.read())
            assert report["draining"] is True
            assert h.server.replica.draining
        finally:
            h.stop()

    def test_batcher_drain_flushes_pending_cohort(self, tmp_path):
        from kubeflow_tpu.serving.batcher import MicroBatcher
        servable = ChaosServable(predict_s=0.02)
        b = MicroBatcher(servable, max_batch=4, max_latency_ms=1.0)
        futures = [b.submit([[float(i)]]) for i in range(4)]
        report = b.drain(timeout_s=5.0)
        for f in futures:
            assert f.result(timeout=1) is not None  # flushed, not lost
        assert report["failed"] == 0
        with pytest.raises(RuntimeError):
            b.submit([[9.0]])  # the door is closed

    def test_drain_during_continuous_batching_loses_nothing(self):
        """ISSUE 18 drill: a drain landing mid-continuous-admission is
        still zero-loss — every request racing the drain either rides
        a flushed cohort to 200 or bounces with a retryable 503 (the
        fleet re-routes it); nothing hangs, nothing hard-fails."""
        h = ServingReplicaHarness("cd0", predict_s=0.03, max_batch=4,
                                  max_latency_ms=1.0)
        h.start()
        try:
            assert h.server.batcher("chaos").batching == "continuous"
            outcomes: list[object] = []
            lock = threading.Lock()

            def fire():
                req = urllib.request.Request(
                    f"{h.url}/v1/models/chaos:predict", data=BODY,
                    method="POST",
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=10) as r:
                        r.read()
                        out = r.status
                except urllib.error.HTTPError as e:
                    e.read()
                    out = e.code
                with lock:
                    outcomes.append(out)

            threads = [threading.Thread(target=fire) for _ in range(8)]
            for t in threads:
                t.start()
            time.sleep(0.02)   # let admission start mid-stream
            report = h.server.drain(timeout_s=5.0)
            for t in threads:
                t.join(timeout=15.0)
            assert not any(t.is_alive() for t in threads), "request hung"
            assert report["inFlightRemaining"] == 0
            # zero loss: only success or a retryable shed, never 4xx/hang
            assert set(outcomes) <= {200, 503}, outcomes
            assert outcomes.count(200) >= 1   # the admitted cohort flushed
        finally:
            h.stop()

    def test_batcher_shutdown_fails_fast_with_drained_outcome(
            self, tmp_path):
        from kubeflow_tpu.serving.batcher import MicroBatcher
        from kubeflow_tpu.serving.request_trace import ServingObs
        sink = str(tmp_path / "drained.jsonl")
        obs = ServingObs(span_path=sink, sample_every=0)
        servable = ChaosServable(predict_s=0.01)
        servable.wedge()   # the loop jams: queued work cannot flush
        b = MicroBatcher(servable, max_batch=2, max_latency_ms=0.1)
        ctxs = [obs.begin("chaos") for _ in range(3)]
        futures = [b.submit([[1.0]], ctx=c) for c in ctxs]
        failed = b.shutdown(join_timeout=0.2)
        assert failed >= 1
        # a queued request must never hang: every straggler future is
        # resolved with an explicit error...
        resolved = 0
        for f in futures:
            if f.done():
                with pytest.raises(RuntimeError, match="drained"):
                    f.result(timeout=0)
                resolved += 1
        assert resolved == failed
        obs.close()
        # ...and its ledger outcome reads drained
        drained = [s for s in load_spans(sink)
                   if s.get("name") == gp.SERVING_REQUEST_SPAN
                   and (s.get("attrs") or {}).get("outcome") ==
                   "drained"]
        assert len(drained) == failed
        servable.unwedge()

    @pytest.mark.skipif(
        not __import__("kubeflow_tpu.serving.grpc_server",
                       fromlist=["HAVE_GRPC"]).HAVE_GRPC,
        reason="grpcio not available")
    def test_grpc_rejects_new_rpcs_while_draining(self):
        import grpc as grpc_mod

        from kubeflow_tpu.serving import tpu_serving_pb2 as pb
        from kubeflow_tpu.serving.grpc_server import (GrpcPredictServer,
                                                      predict_stub)
        h = ServingReplicaHarness("g0", predict_s=0.001)
        h.start()
        g = GrpcPredictServer(h.server, port=0, drain_grace_s=2.0)
        gport = g.start()
        try:
            h.server.replica.set_draining(True)
            channel = grpc_mod.insecure_channel(f"127.0.0.1:{gport}")
            stub = predict_stub(channel)
            req = pb.PredictRequest()
            req.model_spec.name = "chaos"
            req.inputs["instances"].tensor_shape.dim.add().size = 1
            req.inputs["instances"].dtype = pb.DT_FLOAT
            req.inputs["instances"].float_val.append(1.0)
            with pytest.raises(grpc_mod.RpcError) as err:
                stub["Predict"](req, timeout=5.0)
            assert err.value.code() == \
                grpc_mod.StatusCode.UNAVAILABLE
            channel.close()
        finally:
            g.stop(grace=0.1)
            h.stop()


# ----------------------------------------------------- client contract


class TestClientRetries:
    def test_client_propagates_rid_and_deadline_and_retries(self):
        from http.server import BaseHTTPRequestHandler, HTTPServer

        from kubeflow_tpu.serving.client import predict
        seen = []

        class Stub(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                seen.append({
                    "rid": self.headers.get(REQUEST_ID_HEADER),
                    "deadline": self.headers.get(DEADLINE_HEADER)})
                if len(seen) < 3:
                    # two 503s with Retry-After, then success
                    body = b'{"error": "busy"}'
                    self.send_response(503)
                    self.send_header("Retry-After", "0.01")
                    self.send_header("Content-Length",
                                     str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                body = b'{"predictions": [[1.0]]}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = HTTPServer(("127.0.0.1", 0), Stub)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            out = predict(f"127.0.0.1:{port}", "m", [[1.0]],
                          timeout_s=10.0, request_id="cli1",
                          retries=3, backoff_s=0.01)
            assert out == {"predictions": [[1.0]]}
            assert len(seen) == 3
            # ONE request id across every attempt; the deadline budget
            # shrinks monotonically as attempts burn it
            assert {s["rid"] for s in seen} == {"cli1"}
            deadlines = [float(s["deadline"]) for s in seen]
            assert deadlines == sorted(deadlines, reverse=True)
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_client_does_not_retry_meaning(self):
        from http.server import BaseHTTPRequestHandler, HTTPServer

        from kubeflow_tpu.serving.client import predict
        hits = []

        class Stub(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                hits.append(1)
                body = b'{"error": "bad dtype"}'
                self.send_response(400)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = HTTPServer(("127.0.0.1", 0), Stub)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        try:
            with pytest.raises(urllib.error.HTTPError):
                predict(f"127.0.0.1:{port}", "m", [[1.0]],
                        timeout_s=5.0, retries=3, backoff_s=0.01)
            assert len(hits) == 1   # 4xx is meaning, not weather
        finally:
            httpd.shutdown()
            httpd.server_close()


# -------------------------------------------------- metrics + registry


class TestFleetMetrics:
    def test_breaker_series_pruned_on_replica_removal(self):
        reg = Registry()
        router = FleetRouter(registry=reg)
        router.add_replica("gone", "http://127.0.0.1:1")
        router.replica("gone").breaker.eject()
        router._refresh_breaker_gauges()
        assert 'replica="gone"' in reg.render()
        router.remove_replica("gone")
        # the model-unload prune rule: no frozen series for a gone
        # replica anywhere in the exposition
        assert 'replica="gone"' not in reg.render()
        router.close()

    def test_replica_state_drained_outcome_prunes_clean(self):
        from kubeflow_tpu.serving.replica_state import ReplicaState
        reg = Registry()
        rs = ReplicaState(reg)
        rs.observe_request("m", 0.01, outcome="drained")
        rs.refresh()
        assert 'outcome="drained"' in reg.render()
        rs.prune([])
        assert 'model="m"' not in reg.render()

    def test_uptime_and_draining_on_metrics(self):
        from kubeflow_tpu.serving.replica_state import ReplicaState
        reg = Registry()
        rs = ReplicaState(reg, clock=FakeClock(0.0))
        rs.clock.advance(12.5) if hasattr(rs.clock, "advance") else None
        rs.set_draining(True)
        rs.refresh()
        text = reg.render()
        assert "kftpu_serving_draining 1" in text
        assert "kftpu_serving_uptime_seconds 12.5" in text


# ------------------------------------------------------- manifest knobs


class TestServingManifest:
    def render(self, **kw):
        from kubeflow_tpu.manifests.serving import tpu_serving
        return tpu_serving(num_replicas=3, drain_timeout_s=7.0, **kw)

    def test_probes_prestop_and_pdb_rendered(self):
        objs = self.render()
        dep = next(o for o in objs if o["kind"] == "Deployment")
        spec = dep["spec"]["template"]["spec"]
        c = spec["containers"][0]
        assert c["readinessProbe"]["httpGet"]["path"] == "/healthz"
        assert c["livenessProbe"]["httpGet"]["path"] == \
            "/healthz?live=1"
        assert c["lifecycle"]["preStop"]["httpGet"]["path"] == "/drain"
        assert "--drain-timeout=7.0" in c["args"]
        assert spec["terminationGracePeriodSeconds"] == 27
        pdb = next(o for o in objs
                   if o["kind"] == "PodDisruptionBudget")
        assert pdb["apiVersion"] == "policy/v1"
        assert pdb["spec"]["minAvailable"] == 2
        assert dep["spec"]["replicas"] == 3

    def test_single_replica_gets_no_pdb(self):
        from kubeflow_tpu.manifests.serving import tpu_serving
        objs = tpu_serving(num_replicas=1)
        assert not [o for o in objs
                    if o["kind"] == "PodDisruptionBudget"]

    def test_example_component_is_a_three_replica_fleet(self):
        from kubeflow_tpu.manifests.serving import tpu_serving_simple
        objs = tpu_serving_simple()
        dep = next(o for o in objs if o["kind"] == "Deployment")
        assert dep["spec"]["replicas"] == 3
        assert [o for o in objs
                if o["kind"] == "PodDisruptionBudget"]


# ------------------------------------------------------ chaos servable


class TestChaosServable:
    def test_fault_menu(self):
        s = ChaosServable(predict_s=0.0)
        s.fail_next(1, status=500)
        with pytest.raises(RuntimeError) as err:
            s.predict([[1.0]])
        assert err.value.http_status == 500
        assert s.predict([[1.0]]) == [[1.0]]   # budget spent
        s.slow_start(1, 0.05)
        t0 = time.monotonic()
        s.predict([[1.0]])
        assert time.monotonic() - t0 >= 0.05
        t0 = time.monotonic()
        s.predict([[1.0]])
        assert time.monotonic() - t0 < 0.04    # back to fast

    def test_wedge_blocks_until_unwedged(self):
        s = ChaosServable(predict_s=0.0)
        s.wedge()
        done = threading.Event()

        def call():
            s.predict([[1.0]])
            done.set()

        threading.Thread(target=call, daemon=True).start()
        assert not done.wait(0.1)
        s.unwedge()
        assert done.wait(2.0)

    def test_pause_window_stalls_predicts(self):
        s = ChaosServable(predict_s=0.0, pause_every_s=10.0,
                          pause_s=0.05)
        # phase chosen so "now" lands inside the pause window
        s.pause_phase_s = -(time.monotonic() % 10.0) + 0.001
        t0 = time.monotonic()
        s.predict([[1.0]])
        assert time.monotonic() - t0 >= 0.02
