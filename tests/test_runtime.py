"""Tests for the JAX runtime layer: mesh, sharding rules, train step,
bootstrap, metrics, checkpoint — all on the virtual 8-device CPU mesh."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.api.topology import TopologyContract, parse_topology
from kubeflow_tpu.api.trainingjob import ShardingSpec
from kubeflow_tpu.parallel.mesh import (build_mesh, data_axes,
                                        local_batch_size)
from kubeflow_tpu.parallel.sharding_rules import (LogicalRules,
                                                  TRANSFORMER_RULES)
from kubeflow_tpu.runtime.bootstrap import initialize, sharding_from_env
from kubeflow_tpu.runtime.metrics import MetricsLogger
from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

pytestmark = pytest.mark.compute  # JAX trace/compile tests: excluded from smoke tier


class TestMesh:
    def test_default_mesh_is_pure_dp(self):
        mesh = build_mesh()
        assert mesh.shape["data"] == 8
        assert all(mesh.shape[a] == 1 for a in mesh.axis_names if a != "data")

    def test_dp_tp_mesh(self):
        mesh = build_mesh(ShardingSpec(data=2, tensor=4))
        assert mesh.shape["data"] == 2 and mesh.shape["tensor"] == 4

    def test_data_axes_includes_fsdp(self):
        mesh = build_mesh(ShardingSpec(data=2, fsdp=4))
        assert data_axes(mesh) == ("data", "fsdp")
        assert local_batch_size(64, mesh) == 8

    def test_local_batch_must_divide(self):
        mesh = build_mesh(ShardingSpec(data=8))
        with pytest.raises(ValueError):
            local_batch_size(12, mesh)


class TestLogicalRules:
    def test_spec_drops_size1_axes(self):
        mesh = build_mesh(ShardingSpec(data=8))  # tensor axis size 1
        spec = TRANSFORMER_RULES.spec_for(("embed", "mlp"), mesh)
        assert spec == jax.sharding.PartitionSpec()  # all collapsed

    def test_axis_used_once_per_param(self):
        rules = LogicalRules([("a", "tensor"), ("b", "tensor")])
        mesh = build_mesh(ShardingSpec(data=2, tensor=4))
        spec = rules.spec_for(("a", "b"), mesh)
        assert spec == jax.sharding.PartitionSpec("tensor")  # b replicated

    def test_multi_axis_target(self):
        mesh = build_mesh(ShardingSpec(data=2, fsdp=4))
        spec = TRANSFORMER_RULES.spec_for(("batch", None), mesh)
        assert spec == jax.sharding.PartitionSpec(("data", "fsdp"))


class TestBootstrap:
    def test_no_env_local_mesh(self):
        ctx = initialize(env={})
        assert ctx.contract is None
        assert ctx.mesh.shape["data"] == 8
        assert ctx.is_coordinator

    def test_contract_fallback_nonstrict(self):
        topo = parse_topology("v5e-32")
        contract = TopologyContract("c:1", 1, 0, topo)
        env = {**contract.to_env(),
               "KFTPU_SHARDING": json.dumps({"data": 2, "tensor": 4})}
        ctx = initialize(env=env)  # 8 visible != 32 promised -> refit
        assert ctx.mesh.shape["tensor"] == 4  # 2x4=8 still fits

    def test_contract_strict_raises(self):
        topo = parse_topology("v5e-32")
        env = TopologyContract("c:1", 1, 0, topo).to_env()
        with pytest.raises(RuntimeError, match="promises 32"):
            initialize(env=env, strict=True)

    def test_sharding_from_env(self):
        s = sharding_from_env({"KFTPU_SHARDING": json.dumps(
            {"data": 1, "fsdp": 8, "tensor": 1, "pipeline": 1,
             "sequence": 1, "expert": 1})})
        assert s.fsdp == 8


def _linear_spec():
    """Tiny pure-linen-free workload for fast trainstep tests."""

    def init_fn(rng):
        k1, k2 = jax.random.split(rng)
        params = {"w": jax.random.normal(k1, (16, 4)) * 0.1,
                  "b": jnp.zeros((4,))}
        return params, {}

    def loss_fn(params, variables, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, {}

    def batch_fn(rng, bs):
        k1, k2 = jax.random.split(rng)
        return {"x": jax.random.normal(k1, (bs, 16)),
                "y": jax.random.normal(k2, (bs, 4))}

    return init_fn, loss_fn, batch_fn


class TestTrainStep:
    def test_loss_decreases_dp(self):
        init_fn, loss_fn, batch_fn = _linear_spec()
        mesh = build_mesh(ShardingSpec(data=8))
        b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn,
                             optimizer=optax.sgd(0.1))
        state = b.init(init_fn, jax.random.PRNGKey(0))
        step = b.build()
        losses = []
        rng = jax.random.PRNGKey(1)
        for i in range(10):
            rng, k = jax.random.split(rng)
            batch = b.place_batch(batch_fn(jax.random.PRNGKey(42), 16))
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.5
        assert int(state.step) == 10

    def test_tp_matches_dp_numerics(self):
        """The same training run under TP and pure DP must agree — the
        collectives XLA inserts are numerically transparent."""
        init_fn, loss_fn, batch_fn = _linear_spec()
        rules = LogicalRules([("in", "fsdp"), ("out", "tensor")])
        axes = {"w": ("in", "out"), "b": ("out",)}
        results = {}
        for name, spec in [("dp", ShardingSpec(data=8)),
                           ("tp", ShardingSpec(data=2, fsdp=2, tensor=2))]:
            mesh = build_mesh(spec)
            b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn,
                                 optimizer=optax.sgd(0.1), rules=rules,
                                 param_logical_axes=axes)
            state = b.init(init_fn, jax.random.PRNGKey(0))
            step = b.build()
            batch = b.place_batch(batch_fn(jax.random.PRNGKey(7), 16))
            for _ in range(3):
                state, m = step(state, batch)
            results[name] = float(m["loss"])
        np.testing.assert_allclose(results["dp"], results["tp"], rtol=1e-5)

    def test_params_actually_sharded(self):
        init_fn, loss_fn, _ = _linear_spec()
        rules = LogicalRules([("in", None), ("out", "tensor")])
        mesh = build_mesh(ShardingSpec(data=2, tensor=4))
        b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn,
                             optimizer=optax.adam(1e-3), rules=rules,
                             param_logical_axes={"w": ("in", "out"),
                                                 "b": ("out",)})
        state = b.init(init_fn, jax.random.PRNGKey(0))
        assert state.params["w"].sharding.spec == \
            jax.sharding.PartitionSpec(None, "tensor")
        # adam moments shard like their params
        mu_w = state.opt_state[0].mu["w"]
        assert mu_w.sharding.spec == jax.sharding.PartitionSpec(None, "tensor")

    def test_same_shape_params_keep_distinct_moment_shardings(self):
        """Two same-shape params sharded differently: each moment must carry
        its own param's sharding (structural walk, not a shape dict)."""
        def init_fn(rng):
            k1, k2 = jax.random.split(rng)
            return {"a": jax.random.normal(k1, (16, 16)),
                    "b": jax.random.normal(k2, (16, 16))}, {}

        def loss_fn(params, variables, batch, rng):
            pred = batch["x"] @ params["a"] @ params["b"]
            return jnp.mean((pred - batch["y"]) ** 2), {}

        def batch_fn(rng, bs):
            k1, k2 = jax.random.split(rng)
            return {"x": jax.random.normal(k1, (bs, 16)),
                    "y": jax.random.normal(k2, (bs, 16))}

        rules = LogicalRules([("row", "tensor"), ("col", "tensor")])
        axes = {"a": ("row", None), "b": (None, "col")}
        mesh = build_mesh(ShardingSpec(data=2, tensor=4))
        b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn,
                             optimizer=optax.adam(1e-3), rules=rules,
                             param_logical_axes=axes)
        state = b.init(init_fn, jax.random.PRNGKey(0))
        P = jax.sharding.PartitionSpec
        assert state.params["a"].sharding.spec == P("tensor")
        assert state.params["b"].sharding.spec == P(None, "tensor")
        mu = state.opt_state[0].mu
        assert mu["a"].sharding.spec == P("tensor")
        assert mu["b"].sharding.spec == P(None, "tensor")
        # and a step preserves the layouts (no resharding drift)
        step = b.build()
        state, _ = step(state, b.place_batch(batch_fn(jax.random.PRNGKey(1), 16)))
        assert state.opt_state[0].mu["a"].sharding.spec == P("tensor")
        assert state.opt_state[0].mu["b"].sharding.spec == P(None, "tensor")


class TestTinyModels:
    def test_transformer_tiny_trains(self):
        from kubeflow_tpu.models import transformer as T
        from kubeflow_tpu.runtime.worker import train
        ctx = initialize(env={"KFTPU_SHARDING": json.dumps(
            {"data": 2, "fsdp": 2, "tensor": 2})})
        r = train(workload="transformer", steps=2, global_batch=8, ctx=ctx)
        assert r.steps == 2
        assert r.final_metrics["loss"] > 0

    def test_transformer_logical_axes_cover_all_params(self):
        from kubeflow_tpu.models import transformer as T
        cfg = T.TransformerConfig.tiny()
        model = T.TransformerLM(cfg)
        params = jax.eval_shape(
            lambda rng: model.init(rng, jnp.zeros((1, 8), jnp.int32)),
            jax.random.PRNGKey(0))["params"]
        axes = T.logical_axes(params)
        flat = jax.tree.leaves(
            axes, is_leaf=lambda x: isinstance(x, tuple))
        # every kernel/embedding got a non-trivial annotation
        annotated = [a for a in flat if any(x is not None for x in a)]
        assert len(annotated) >= cfg.num_layers * 4 + 3


class TestMetrics:
    def test_summary_skips_warmup(self, tmp_path):
        m = MetricsLogger(str(tmp_path / "m.jsonl"), batch_size=10,
                          log_every=0)
        import time
        for i in range(3):
            m.start_step()
            time.sleep(0.01)
            m.end_step(i + 1, {"loss": 1.0})
        s = m.summary(warmup=1)
        assert s["steps"] == 3
        assert s["examples_per_sec"] > 0
        m.close()
        lines = (tmp_path / "m.jsonl").read_text().strip().splitlines()
        assert len(lines) == 3 and json.loads(lines[0])["loss"] == 1.0


@pytest.mark.slow
class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        init_fn, loss_fn, batch_fn = _linear_spec()
        mesh = build_mesh(ShardingSpec(data=8))
        b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn,
                             optimizer=optax.sgd(0.1))
        state = b.init(init_fn, jax.random.PRNGKey(0))
        step = b.build()
        state, _ = step(state, b.place_batch(batch_fn(jax.random.PRNGKey(1), 16)))
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(1, state, force=True)
        mgr.wait()
        restored = mgr.restore(state)
        np.testing.assert_allclose(np.asarray(restored.params["w"]),
                                   np.asarray(state.params["w"]))
        assert int(restored.step) == 1
        mgr.close()

    def test_gang_restart_resumes_worker_at_checkpoint(self, tmp_path):
        """The full resumeFrom loop: a worker trains N steps writing
        checkpoints; its pod fails; the operator gang-restarts and sets
        spec.resumeFrom; the recreated gang's worker restores and continues
        from the last step instead of step 0 (VERDICT r1 item 3)."""
        from kubeflow_tpu.cluster import FakeCluster
        from kubeflow_tpu.controllers.runtime import Manager
        from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
        from kubeflow_tpu.runtime.worker import train

        ckpt_dir = str(tmp_path / "ckpt")
        # gang #1's worker: 3 steps, checkpoint every step, then "dies"
        r1 = train(workload="transformer", steps=3, global_batch=8,
                   checkpoint_dir=ckpt_dir, checkpoint_every=1)
        assert r1.steps == 3

        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create({
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "train", "namespace": "kubeflow"},
            "spec": {"checkpointDir": ckpt_dir,
                     "replicaSpecs": {"TPU": {
                         "tpuTopology": "v5e-8",
                         "template": {"spec": {"containers": [
                             {"name": "jax", "image": "trainer:v1"}]}}}}}})
        for _ in range(3):
            mgr.run_pending()
            cluster.tick()
        mgr.run_pending()
        cluster.fail_pod("kubeflow", "train-worker-0-1")
        mgr.run_pending()
        pod = cluster.get("v1", "Pod", "kubeflow", "train-worker-0-0")
        env_map = {e["name"]: e["value"]
                   for e in pod["spec"]["containers"][0]["env"]}
        assert env_map["KFTPU_RESUME_FROM"] == ckpt_dir
        # gang #2's worker, driven by the operator-rendered env: asked for
        # 5 total steps, it restores at 3 and executes only 2
        r2 = train(workload="transformer", steps=5, global_batch=8,
                   resume_from=env_map["KFTPU_RESUME_FROM"])
        assert r2.steps == 2


class TestRecipe:
    """Training recipes (runtime/recipe.py): the tf_cnn_benchmarks flag
    surface — schedules, weight decay masking, eval pass."""

    def test_warmup_cosine_shape(self):
        from kubeflow_tpu.runtime.recipe import lr_schedule
        s = lr_schedule("cosine", 0.4, total_steps=100, warmup_steps=10)
        assert float(s(0)) == pytest.approx(0.0)
        assert float(s(10)) == pytest.approx(0.4, rel=1e-3)
        assert float(s(55)) < 0.4
        assert float(s(99)) < 0.01

    def test_step_decay_boundaries(self):
        from kubeflow_tpu.runtime.recipe import lr_schedule
        s = lr_schedule("step", 1.0, total_steps=90, warmup_steps=0)
        assert float(s(0)) == pytest.approx(1.0)
        assert float(s(31)) == pytest.approx(0.1)
        assert float(s(61)) == pytest.approx(0.01)
        assert float(s(85)) == pytest.approx(0.001)

    def test_decay_mask_kernels_only(self):
        import jax.numpy as jnp
        from kubeflow_tpu.runtime.recipe import decay_mask
        params = {"conv": {"kernel": jnp.zeros((3, 3, 4, 8))},
                  "bn": {"scale": jnp.zeros((8,)), "bias": jnp.zeros((8,))},
                  "head": {"kernel": jnp.zeros((8, 2)),
                           "bias": jnp.zeros((2,))}}
        m = decay_mask(params)
        assert m["conv"]["kernel"] and m["head"]["kernel"]
        assert not m["bn"]["scale"] and not m["bn"]["bias"]
        assert not m["head"]["bias"]

    def test_unknown_names_rejected(self):
        from kubeflow_tpu.runtime.recipe import make_optimizer, lr_schedule
        with pytest.raises(ValueError, match="optimizer"):
            make_optimizer("sgdd", 0.1)
        with pytest.raises(ValueError, match="schedule"):
            lr_schedule("cosinee", 0.1, 10)

    def test_worker_full_recipe_with_eval(self):
        """The worker loop with the ImageNet-style recipe on a tiny
        resnet18: schedules, decay, smoothing, and the top-1/top-5 eval
        pass all under one run."""
        from kubeflow_tpu.runtime.worker import train
        r = train(workload="resnet18", steps=4, global_batch=16,
                  learning_rate=0.1, sync_every=2,
                  workload_kwargs={"image_size": 32, "num_classes": 10},
                  optimizer="momentum", lr_schedule="cosine",
                  warmup_steps=1, weight_decay=1e-4, label_smoothing=0.1,
                  eval_every=2, eval_batches=2, seed=3)
        assert r.steps == 4
        for key in ("loss", "learning_rate", "top1", "top5", "eval_loss"):
            assert key in r.final_metrics, r.final_metrics
        assert 0.0 <= r.final_metrics["top1"] <= r.final_metrics["top5"] <= 1.0
        import numpy as np
        assert np.isfinite(r.final_metrics["loss"])

    def test_label_smoothing_raises_floor(self):
        import jax.numpy as jnp
        from kubeflow_tpu.models.resnet import cross_entropy_loss
        logits = jnp.array([[10.0, -10.0, -10.0]])
        labels = jnp.array([0])
        hard = float(cross_entropy_loss(logits, labels))
        soft = float(cross_entropy_loss(logits, labels, 0.1))
        assert soft > hard  # smoothing penalizes overconfidence


class TestPreemption:
    def test_preemption_checkpoints_and_exits_cleanly(self, tmp_path,
                                                      monkeypatch):
        """Preemption contract: stop flag mid-run → finish the step, force
        a checkpoint off-cadence, return preempted=True; a resumed run
        continues from the preempted step with nothing lost."""
        from kubeflow_tpu.runtime import worker

        class FlipAfterReads:
            """Guard whose stop flag flips True after N reads — a
            deterministic stand-in for SIGTERM arriving mid-loop."""
            def __init__(self, install=True, on_term=None):
                self.reads = 0
            @property
            def stop(self):
                self.reads += 1
                return self.reads > 6  # ~3 loop iterations (2 reads each)
            def uninstall(self):
                pass

        monkeypatch.setattr(worker, "PreemptionGuard", FlipAfterReads)
        ckpt = str(tmp_path / "ckpt")
        kw = dict(workload="transformer", global_batch=16, sync_every=1,
                  checkpoint_dir=ckpt, checkpoint_every=1000,
                  workload_kwargs={})
        r = worker.train(steps=200, **kw)
        assert r.preempted
        assert 0 < r.steps < 200
        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        mgr = CheckpointManager(ckpt)
        assert mgr.latest_step() == r.steps  # forced save, cadence ignored
        mgr.close()
        # resume: real guard again; picks up at the preempted step and
        # runs only the remaining steps (nothing replayed)
        monkeypatch.undo()
        r2 = worker.train(steps=r.steps + 2, **kw)
        assert not r2.preempted
        assert r2.steps == 2  # steps run THIS process: target − resumed
        mgr = CheckpointManager(ckpt)
        assert mgr.latest_step() == r.steps + 2
        mgr.close()

    def test_sigterm_sets_stop_and_uninstall_restores(self):
        import os
        import signal
        import time
        from kubeflow_tpu.runtime.worker import PreemptionGuard
        before = signal.getsignal(signal.SIGTERM)
        guard = PreemptionGuard()
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.time() + 5
            while not guard.stop and time.time() < deadline:
                time.sleep(0.01)
            assert guard.stop
        finally:
            guard.uninstall()
        assert signal.getsignal(signal.SIGTERM) is before

    def test_train_restores_sigterm_handler(self):
        import signal
        from kubeflow_tpu.runtime.worker import train
        before = signal.getsignal(signal.SIGTERM)
        train(workload="transformer", steps=1, global_batch=16,
              workload_kwargs={})
        assert signal.getsignal(signal.SIGTERM) is before


class TestTransformerEval:
    def test_eval_reports_perplexity(self):
        from kubeflow_tpu.runtime.worker import train
        r = train(workload="transformer", steps=2, global_batch=16,
                  sync_every=1, eval_every=2, eval_batches=2,
                  workload_kwargs={})
        assert "eval_perplexity" in r.final_metrics
        assert "eval_token_accuracy" in r.final_metrics
        assert r.final_metrics["eval_perplexity"] > 1.0
