"""Lint tier: the reference ran flake8 over the tree in CI
(testing/test_flake8.py); no third-party linter ships in this image, so
utils/lint.py implements the checks the suite relies on (syntax, unused
imports, same-scope import redefinition, bare except)."""

import os
import textwrap

from kubeflow_tpu.utils.lint import check_file, check_tree

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_tree_is_clean():
    findings = check_tree(REPO_ROOT, ("kubeflow_tpu", "tests"))
    assert not findings, "\n" + "\n".join(str(f) for f in findings)


def test_step_engine_knobs_cover_the_operator_surface():
    """Every TrainStepBuilder field tagged operator_knob must be
    representable end-to-end: a modes vocabulary in runtime/recipe.py, a
    train()/CLI surface in runtime/worker.py, a TPUJob spec field parsed
    and serialized by api/trainingjob.py, a KFTPU_* env rendered by
    controllers/tpujob.py, and a manifests/training.py schema entry — so
    a future step-engine option can't silently bypass the operator."""
    import dataclasses
    import inspect

    from kubeflow_tpu.api.trainingjob import TrainingJob
    from kubeflow_tpu.runtime import recipe, worker
    from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

    def src(*rel):
        with open(os.path.join(REPO_ROOT, "kubeflow_tpu", *rel)) as f:
            return f.read()

    knobs = [f for f in dataclasses.fields(TrainStepBuilder)
             if f.metadata.get("operator_knob")]
    assert knobs, "expected at least the weight_update knob"
    job_fields = {f.name for f in dataclasses.fields(TrainingJob)}
    worker_src = src("runtime", "worker.py")
    controller_src = src("controllers", "tpujob.py")
    api_src = src("api", "trainingjob.py")
    manifests_src = src("manifests", "training.py")
    for knob in knobs:
        # recipe: the vocabulary exists and contains the builder default
        modes = getattr(recipe, knob.metadata["modes"])
        assert knob.default in modes, (knob.name, modes)
        # worker: a train() parameter and a CLI flag
        assert knob.name in inspect.signature(worker.train).parameters
        assert f"--{knob.name.replace('_', '-')}" in worker_src
        # api: a typed TrainingJob field, parsed from and serialized to
        # the declared spec field
        spec_field = knob.metadata["spec_field"]
        assert knob.name in job_fields
        assert f'spec.get("{spec_field}"' in api_src
        assert f'"{spec_field}"' in api_src
        # controller: rendered into worker env
        env = "KFTPU_" + knob.name.upper()
        assert env in controller_src, (knob.name, env)
        assert env in worker_src
        # manifests: the CRD schema / example renderer names the field
        assert spec_field in manifests_src, (knob.name, spec_field)


def test_input_pipeline_knobs_are_plumbed_end_to_end():
    """Every InputSpec field must be representable end-to-end, the same
    rule as runPolicy/weightUpdate: parsed+serialized through the TPUJob
    spec's ``input`` block (api/trainingjob.py), rendered into worker env
    by the controller, consumed by the worker's train()/CLI surface, and
    named in the manifests CRD schema + example builder — so a future
    input knob can't silently exist in one layer only."""
    import dataclasses

    from kubeflow_tpu.api.trainingjob import InputSpec, TrainingJob
    from kubeflow_tpu.manifests.training import tpu_job_simple
    from kubeflow_tpu.runtime import worker

    def src(*rel):
        with open(os.path.join(REPO_ROOT, "kubeflow_tpu", *rel)) as f:
            return f.read()

    knobs = dataclasses.fields(InputSpec)
    assert knobs, "expected the workers/device_prefetch knobs"
    worker_src = src("runtime", "worker.py")
    controller_src = src("controllers", "tpujob.py")
    manifests_src = src("manifests", "training.py")
    import inspect
    train_params = inspect.signature(worker.train).parameters
    for knob in knobs:
        env = knob.metadata["env"]
        # worker: a CLI flag and the env fallback
        assert knob.metadata["cli"] in worker_src, knob.name
        assert env in worker_src, knob.name
        # controller: rendered into worker env (via InputSpec.to_env,
        # whose env names are asserted against the worker above)
        assert "input_spec.to_env" in controller_src
        # manifests: the CRD schema names the spec field
        assert f'"{knob.metadata["spec_field"]}"' in manifests_src, knob.name
    # train() consumes both knobs by their canonical names
    assert "input_workers" in train_params
    assert "device_prefetch" in train_params

    # spec wire round-trip: to_dict → from_manifest → identical spec,
    # and the controller env render matches the declared names
    ispec = InputSpec(workers=3, device_prefetch=5)
    manifest = {
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "t", "namespace": "ns"},
        "spec": {"replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [{"name": "c"}]}}}},
            "input": ispec.to_dict()},
    }
    job = TrainingJob.from_manifest(manifest)
    assert job.input_spec == ispec
    assert job.to_manifest()["spec"]["input"] == ispec.to_dict()
    assert ispec.to_env() == {"KFTPU_INPUT_WORKERS": "3",
                              "KFTPU_DEVICE_PREFETCH": "5"}

    # admission rejects garbage (a typo'd knob must fail at apply)
    import pytest
    with pytest.raises(ValueError, match="input"):
        InputSpec.from_dict({"workers": -1})
    with pytest.raises(ValueError, match="unknown"):
        InputSpec.from_dict({"worker": 2})
    with pytest.raises(ValueError, match="mapping"):
        InputSpec.from_dict([4, 2])   # YAML list typo

    # example builder renders the block end to end
    ex = next(o for o in tpu_job_simple(input_workers=3, device_prefetch=5)
              if o["kind"] == "TPUJob")
    assert ex["spec"]["input"] == {"workers": 3, "devicePrefetch": 5}
    assert TrainingJob.from_manifest(ex).input_spec == ispec


def test_obs_knobs_are_plumbed_end_to_end():
    """Every ObsSpec field must be representable end-to-end, the same
    rule as input/schedulingPolicy: parsed+serialized through the TPUJob
    spec's ``observability`` block (api/trainingjob.py), rendered into
    worker env by the controller, consumed by the worker's train()/CLI
    surface, and named in the manifests CRD schema + example builder —
    and the trace-id contract (minted as an annotation, rendered as
    KFTPU_TRACE_ID) must connect scheduler, operator, and worker, so a
    future observability knob can't silently exist in one layer only."""
    import dataclasses

    from kubeflow_tpu.api.trainingjob import ObsSpec, TrainingJob
    from kubeflow_tpu.manifests.training import tpu_job_simple
    from kubeflow_tpu.obs.trace import (SPAN_PATH_ENV,
                                        TRACE_ID_ANNOTATION, TRACE_ID_ENV)

    def src(*rel):
        with open(os.path.join(REPO_ROOT, "kubeflow_tpu", *rel)) as f:
            return f.read()

    knobs = dataclasses.fields(ObsSpec)
    assert knobs, "expected the spanPath/metricsPort knobs"
    worker_src = src("runtime", "worker.py")
    controller_src = src("controllers", "tpujob.py")
    manifests_src = src("manifests", "training.py")
    scheduler_src = src("scheduler", "core.py")
    for knob in knobs:
        # worker: a CLI flag and the env fallback
        assert knob.metadata["cli"] in worker_src, knob.name
        assert knob.metadata["env"] in worker_src \
            or knob.metadata["env"] == SPAN_PATH_ENV, knob.name
        # controller: rendered into worker env (via ObsSpec.to_env)
        assert "obs_spec.to_env" in controller_src
        # manifests: the CRD schema names the spec field
        assert f'"{knob.metadata["spec_field"]}"' in manifests_src, \
            knob.name
    # the trace-id contract: minted+persisted through the ONE shared
    # helper (controllers/runtime.py ensure_trace_id — the binding_of
    # pattern) by BOTH control-plane components, then rendered into
    # worker env and consumed by the worker
    runtime_src = src("controllers", "runtime.py")
    assert "TRACE_ID_ANNOTATION" in runtime_src
    for component_src in (scheduler_src, controller_src):
        assert "ensure_trace_id" in component_src
        assert "trace_job_event" in component_src
    assert "TRACE_ID_ENV" in controller_src
    assert "TRACE_ID_ENV" in worker_src
    assert SPAN_PATH_ENV in ("KFTPU_SPAN_PATH",)
    assert TRACE_ID_ENV in ("KFTPU_TRACE_ID",)
    assert TRACE_ID_ANNOTATION == "observability.kubeflow.org/trace-id"

    # spec wire round-trip: to_dict → from_manifest → identical spec,
    # and the controller env render matches the declared names
    ospec = ObsSpec(span_path="/var/log/kftpu/spans.jsonl",
                    metrics_port=9100)
    manifest = {
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "t", "namespace": "ns"},
        "spec": {"replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [{"name": "c"}]}}}},
            "observability": ospec.to_dict()},
    }
    job = TrainingJob.from_manifest(manifest)
    assert job.obs_spec == ospec
    assert job.to_manifest()["spec"]["observability"] == ospec.to_dict()
    assert ospec.to_env() == {
        "KFTPU_SPAN_PATH": "/var/log/kftpu/spans.jsonl",
        "KFTPU_OBS_METRICS_PORT": "9100"}

    # train() consumes both knobs by their canonical names
    import inspect

    from kubeflow_tpu.runtime import worker
    train_params = inspect.signature(worker.train).parameters
    assert "span_path" in train_params
    assert "obs_metrics_port" in train_params

    # admission rejects garbage (a typo'd knob must fail at apply)
    import pytest
    with pytest.raises(ValueError, match="metricsPort"):
        ObsSpec.from_dict({"metricsPort": -1})
    with pytest.raises(ValueError, match="unknown"):
        ObsSpec.from_dict({"spanpath": "/x"})
    with pytest.raises(ValueError, match="mapping"):
        ObsSpec.from_dict(["/x"])

    # example builder renders the block end to end
    ex = next(o for o in tpu_job_simple(
        span_path="/var/log/kftpu/spans.jsonl", obs_metrics_port=9100)
        if o["kind"] == "TPUJob")
    assert TrainingJob.from_manifest(ex).obs_spec == ospec


def test_warm_start_knobs_are_plumbed_end_to_end():
    """Every WarmStartSpec field must be representable end-to-end, the
    same rule as input/observability: parsed+serialized through the
    TPUJob spec's ``warmStart`` block (api/trainingjob.py), rendered
    into worker env by the controller, consumed by the worker's
    train()/CLI surface, and named in the manifests CRD schema +
    example builder — and the shared-cache / warm-pool contracts must
    connect their two sides — so a future warm-start knob can't
    silently exist in one layer only."""
    import dataclasses
    import inspect

    from kubeflow_tpu.api.trainingjob import TrainingJob, WarmStartSpec
    from kubeflow_tpu.manifests.training import tpu_job_simple
    from kubeflow_tpu.runtime import worker

    def src(*rel):
        with open(os.path.join(REPO_ROOT, "kubeflow_tpu", *rel)) as f:
            return f.read()

    knobs = dataclasses.fields(WarmStartSpec)
    assert knobs, "expected the aot/aotDir knobs"
    worker_src = src("runtime", "worker.py")
    controller_src = src("controllers", "tpujob.py")
    manifests_src = src("manifests", "training.py")
    for knob in knobs:
        # worker: a CLI flag and the env fallback (env names are owned
        # by runtime/aot.py and asserted below)
        assert knob.metadata["cli"] in worker_src, knob.name
        # controller: rendered into worker env (via WarmStartSpec.to_env)
        assert "warm_start.to_env" in controller_src
        # manifests: the CRD schema names the spec field
        assert f'"{knob.metadata["spec_field"]}"' in manifests_src, \
            knob.name
    # env names are the runtime/aot.py constants on both sides
    from kubeflow_tpu.runtime.aot import AOT_DIR_ENV, AOT_ENABLE_ENV
    assert {k.metadata["env"] for k in knobs} == \
        {AOT_ENABLE_ENV, AOT_DIR_ENV}
    assert "AOT_ENABLE_ENV" in worker_src
    assert "AOT_DIR_ENV" in worker_src or AOT_DIR_ENV in worker_src
    # train() consumes both knobs by their canonical names
    train_params = inspect.signature(worker.train).parameters
    assert "aot" in train_params
    assert "aot_dir" in train_params

    # the shared-cache service: the operator resolves the namespace dir
    # through the ONE helper pair in runtime/compile_cache.py
    assert "SHARED_CACHE_ROOT_ENV" in controller_src
    assert "namespace_cache_dir" in controller_src
    # the warm-pool contract: scheduler maintains, operator adopts,
    # both through scheduler/warmpool.py (the binding_of pattern)
    core_src = src("scheduler", "core.py")
    for consumer, where in (("warmpool.slots_of", core_src),
                            ("warmpool.covered_slots", core_src),
                            ("warmpool.reconcile_warm_pods", core_src),
                            ("warmpool.warm_pod_name", controller_src),
                            ("warmpool.ADOPTED_ANNOTATION",
                             controller_src)):
        assert consumer in where, f"{consumer} not consumed"

    # spec wire round-trip: to_dict → from_manifest → identical spec,
    # and the controller env render matches the declared names
    wspec = WarmStartSpec(aot=True, aot_dir="/ckpt/aot")
    manifest = {
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "t", "namespace": "ns"},
        "spec": {"replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [{"name": "c"}]}}}},
            "warmStart": wspec.to_dict()},
    }
    job = TrainingJob.from_manifest(manifest)
    assert job.warm_start == wspec
    assert job.to_manifest()["spec"]["warmStart"] == wspec.to_dict()
    assert wspec.to_env() == {"KFTPU_AOT": "1",
                              "KFTPU_AOT_DIR": "/ckpt/aot"}
    assert WarmStartSpec(aot=False).to_env() == {"KFTPU_AOT": "0"}

    # admission rejects garbage (a typo'd knob must fail at apply)
    import pytest
    with pytest.raises(ValueError, match="aot"):
        WarmStartSpec.from_dict({"aot": "yes"})
    with pytest.raises(ValueError, match="unknown"):
        WarmStartSpec.from_dict({"aotdir": "/x"})
    with pytest.raises(ValueError, match="mapping"):
        WarmStartSpec.from_dict(["/x"])

    # example builder renders the block end to end
    ex = next(o for o in tpu_job_simple(aot=True, aot_dir="/ckpt/aot")
              if o["kind"] == "TPUJob")
    assert TrainingJob.from_manifest(ex).warm_start == wspec


def test_multislice_knobs_are_plumbed_end_to_end():
    """Every MultisliceSpec field must be representable end-to-end, the
    same rule as input/warmStart: parsed+serialized through the TPUJob
    spec's ``multislice`` block (api/trainingjob.py), rendered into
    worker env by the controller, consumed by the worker's train()/CLI
    surface, and named in the manifests CRD schema + example builder —
    so a future multi-slice knob can't silently exist in one layer
    only."""
    import dataclasses
    import inspect

    import pytest

    from kubeflow_tpu.api.trainingjob import MultisliceSpec, TrainingJob
    from kubeflow_tpu.manifests.training import tpu_job_simple
    from kubeflow_tpu.runtime import worker

    def src(*rel):
        with open(os.path.join(REPO_ROOT, "kubeflow_tpu", *rel)) as f:
            return f.read()

    knobs = dataclasses.fields(MultisliceSpec)
    assert knobs, "expected the pipeline/microbatches knobs"
    worker_src = src("runtime", "worker.py")
    controller_src = src("controllers", "tpujob.py")
    manifests_src = src("manifests", "training.py")
    for knob in knobs:
        # worker: a CLI flag and the env fallback
        assert knob.metadata["cli"] in worker_src, knob.name
        assert knob.metadata["env"] in worker_src, knob.name
        # controller: rendered into worker env (via MultisliceSpec.to_env)
        assert "multislice.to_env" in controller_src
        # manifests: the CRD schema names the spec field
        assert f'"{knob.metadata["spec_field"]}"' in manifests_src, \
            knob.name
    # train() consumes both knobs by their canonical names
    train_params = inspect.signature(worker.train).parameters
    assert "multislice_pipeline" in train_params
    assert "multislice_microbatches" in train_params

    # spec wire round-trip: to_dict → from_manifest → identical spec,
    # and the controller env render matches the declared names
    mspec = MultisliceSpec(pipeline=True, microbatches=8)
    manifest = {
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "t", "namespace": "ns"},
        "spec": {"replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8", "numSlices": 2,
            "template": {"spec": {"containers": [{"name": "c"}]}}}},
            "multislice": mspec.to_dict()},
    }
    job = TrainingJob.from_manifest(manifest)
    assert job.multislice == mspec
    assert job.to_manifest()["spec"]["multislice"] == mspec.to_dict()
    assert mspec.to_env() == {"KFTPU_MULTISLICE_PIPELINE": "1",
                              "KFTPU_MULTISLICE_MICROBATCHES": "8"}

    # admission rejects garbage (a typo'd knob must fail at apply)
    with pytest.raises(ValueError, match="unknown"):
        MultisliceSpec.from_dict({"pipelined": True})
    with pytest.raises(ValueError, match="microbatches"):
        MultisliceSpec.from_dict({"microbatches": -1})
    with pytest.raises(ValueError, match="mapping"):
        MultisliceSpec.from_dict([True])

    # example builder renders the block (and the pipelined workload's
    # command) end to end
    ex = next(o for o in tpu_job_simple(
        num_slices=2, multislice_pipeline=True,
        multislice_microbatches=8)
        if o["kind"] == "TPUJob")
    parsed = TrainingJob.from_manifest(ex)
    assert parsed.multislice == mspec
    assert parsed.tpu_spec.num_slices == 2
    cmd = ex["spec"]["replicaSpecs"]["TPU"]["template"]["spec"][
        "containers"][0]["command"]
    assert "--multislice-pipeline" in cmd


def test_scheduling_policy_is_plumbed_end_to_end():
    """Every SchedulingPolicy field must be representable end-to-end,
    the same rule as runPolicy/input: parsed+serialized through the
    TPUJob spec's ``schedulingPolicy`` block (api/trainingjob.py),
    rendered into worker env AND gated on by the operator
    (controllers/tpujob.py), consumed by the scheduler's queue model
    (scheduler/queue.py), and named in the manifests CRD schema +
    example builder — so a future scheduling knob can't silently exist
    in one layer only."""
    import dataclasses

    from kubeflow_tpu.api.trainingjob import (BINDING_ANNOTATION,
                                              SchedulingPolicy,
                                              TrainingJob)
    from kubeflow_tpu.manifests.training import tpu_job_simple

    def src(*rel):
        with open(os.path.join(REPO_ROOT, "kubeflow_tpu", *rel)) as f:
            return f.read()

    fields = {f.name for f in dataclasses.fields(SchedulingPolicy)}
    assert fields == {"queue", "priority", "preemptible",
                      "min_chips", "max_chips"}, \
        "SchedulingPolicy field added/removed — extend this check"
    controller_src = src("controllers", "tpujob.py")
    manifests_src = src("manifests", "training.py")
    queue_src = src("scheduler", "queue.py")
    # controller: env render + the binding gate both live in the
    # operator, and the gate parses the annotation through the
    # scheduler's OWN binding_of/binding_matches (one wire contract);
    # an elastic binding's shape is ADOPTED (the resize execution path)
    assert "scheduling_policy.to_env" in controller_src
    assert "binding_of" in controller_src
    assert "binding_matches" in controller_src
    assert "_job_at_binding_shape" in controller_src
    # scheduler: every field feeds the queue model
    for name in fields:
        assert name in queue_src, \
            f"SchedulingPolicy.{name} is never consumed by the scheduler"
    # manifests: the CRD schema names every spec field
    for spec_field in ("queue", "priority", "preemptible",
                       "minChips", "maxChips", "schedulingPolicy"):
        assert f'"{spec_field}"' in manifests_src, spec_field

    # spec wire round-trip: to_dict → from_manifest → identical policy;
    # and ABSENT block → None (the managed/unmanaged gate)
    policy = SchedulingPolicy(queue="research", priority=7,
                              preemptible=True)
    manifest = {
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "t", "namespace": "ns"},
        "spec": {"replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [{"name": "c"}]}}}},
            "schedulingPolicy": policy.to_dict()},
    }
    job = TrainingJob.from_manifest(manifest)
    assert job.scheduling_policy == policy
    assert job.to_manifest()["spec"]["schedulingPolicy"] == \
        policy.to_dict()
    del manifest["spec"]["schedulingPolicy"]
    assert TrainingJob.from_manifest(manifest).scheduling_policy is None
    # env render carries every knob under its declared name
    assert policy.to_env() == {"KFTPU_SCHED_QUEUE": "research",
                               "KFTPU_SCHED_PRIORITY": "7",
                               "KFTPU_SCHED_PREEMPTIBLE": "1"}

    # admission rejects garbage (a typo'd knob must fail at apply)
    import pytest
    with pytest.raises(ValueError, match="unknown"):
        SchedulingPolicy.from_dict({"prio": 3})
    with pytest.raises(ValueError, match="priority"):
        SchedulingPolicy.from_dict({"priority": "high"})
    with pytest.raises(ValueError, match="mapping"):
        SchedulingPolicy.from_dict([1, 2])

    # example builder renders the block end to end
    ex = next(o for o in tpu_job_simple(queue="research", priority=7,
                                        preemptible=True)
              if o["kind"] == "TPUJob")
    assert TrainingJob.from_manifest(ex).scheduling_policy == policy
    # the binding annotation name is the one contract both sides share
    assert BINDING_ANNOTATION == "scheduling.kubeflow.org/binding"

    # elastic bounds: spec → env → example round trip, plus the
    # admission guards (nominal inside the envelope; data-parallel
    # wildcard so the mesh can follow a resized chip count)
    elastic = SchedulingPolicy(queue="research", priority=7,
                               preemptible=True, min_chips=4,
                               max_chips=16)
    manifest["spec"]["schedulingPolicy"] = elastic.to_dict()
    job = TrainingJob.from_manifest(manifest)
    assert job.scheduling_policy == elastic
    assert job.scheduling_policy.elastic
    assert job.to_manifest()["spec"]["schedulingPolicy"] == \
        elastic.to_dict()
    env = elastic.to_env()
    assert env["KFTPU_SCHED_MIN_CHIPS"] == "4"
    assert env["KFTPU_SCHED_MAX_CHIPS"] == "16"
    with pytest.raises(ValueError, match="minChips"):
        SchedulingPolicy.from_dict({"minChips": 8, "maxChips": 4})
    with pytest.raises(ValueError, match="envelope|outside"):
        manifest["spec"]["schedulingPolicy"] = {"minChips": 1,
                                                "maxChips": 4}
        TrainingJob.from_manifest(manifest)   # nominal v5e-8 > max 4
    with pytest.raises(ValueError, match="wildcard"):
        manifest["spec"]["schedulingPolicy"] = {"minChips": 4}
        manifest["spec"]["sharding"] = {"data": 8}
        TrainingJob.from_manifest(manifest)
    ex = next(o for o in tpu_job_simple(queue="research", priority=7,
                                        preemptible=True, min_chips=4,
                                        max_chips=16)
              if o["kind"] == "TPUJob")
    assert TrainingJob.from_manifest(ex).scheduling_policy == elastic


def test_node_health_contract_is_shared_not_duplicated():
    """The quarantine/suspect/health annotation contract must have ONE
    definition (api/trainingjob.py) and one parse implementation
    (scheduler/health.py), consumed by BOTH the operator and the
    scheduler — the binding_of rule: the two processes coordinate
    through these annotations, so a string or parse drift between them
    silently breaks migration."""
    import subprocess

    from kubeflow_tpu.api.trainingjob import (HEALTH_ANNOTATION,
                                              QUARANTINE_ANNOTATION,
                                              SUSPECT_ANNOTATION)
    from kubeflow_tpu.scheduler import health
    from kubeflow_tpu.scheduler.queue import SchedulerConfig

    assert HEALTH_ANNOTATION == "kubeflow.org/health"
    assert QUARANTINE_ANNOTATION == "kubeflow.org/quarantine"
    assert SUSPECT_ANNOTATION == "scheduling.kubeflow.org/suspect-host"

    # single definition: each literal appears in exactly one source
    # file (api/trainingjob.py) — every other layer imports the name
    pkg = os.path.join(REPO_ROOT, "kubeflow_tpu")
    for literal in (QUARANTINE_ANNOTATION, SUSPECT_ANNOTATION,
                    HEALTH_ANNOTATION):
        hits = subprocess.run(
            ["grep", "-rl", f'"{literal}"', pkg],
            capture_output=True, text=True).stdout.split()
        assert [os.path.relpath(h, pkg) for h in hits] == \
            [os.path.join("api", "trainingjob.py")], \
            f"{literal!r} defined outside api/trainingjob.py: {hits}"

    def src(*rel):
        with open(os.path.join(pkg, *rel)) as f:
            return f.read()

    # the operator records evidence + suspect through the shared
    # helpers; the scheduler parses/acts through the same module —
    # neither side re-implements the wire format
    controller_src = src("controllers", "tpujob.py")
    assert "health.record_host_event" in controller_src
    assert "SUSPECT_ANNOTATION" in controller_src
    core_src = src("scheduler", "core.py")
    for consumer in ("health.suspect_of", "health.quarantine_of",
                     "health.decayed_score", "health.release_eligible",
                     "health.quarantine_record"):
        assert consumer in core_src, \
            f"scheduler/core.py must consume {consumer}"
    inv_src = src("scheduler", "inventory.py")
    assert "health.is_quarantined" in inv_src
    assert "health.host_cells" in inv_src

    # wire round trips through the one parse implementation
    raw = health.quarantine_record("r", 2.5, 100.0, 60.0)
    node = {"metadata": {"annotations": {QUARANTINE_ANNOTATION: raw}}}
    q = health.quarantine_of(node)
    assert (q["reason"], q["score"], q["since"], q["until"]) == \
        ("r", 2.5, 100.0, 160.0)
    rec = health.fold_event({"score": 0.0, "time": 0.0},
                            health.EVENT_POD_CRASH, 50.0)
    node = {"metadata": {"annotations": {
        HEALTH_ANNOTATION: __import__("json").dumps(rec)}}}
    assert health.health_of(node) == rec

    # the deployed ConfigMap's health block parses into the live config
    # (manifests render ↔ scheduler parse, one schema)
    from kubeflow_tpu.manifests.training import tpu_scheduler
    import json as _json
    cm = next(o for o in tpu_scheduler(health={"enabled": False})
              if o["kind"] == "ConfigMap")
    cfg = SchedulerConfig.from_dict(
        _json.loads(cm["data"]["config.json"]))
    assert cfg.health.enabled is False
    import pytest
    with pytest.raises(ValueError, match="unknown"):
        tpu_scheduler(health={"quarantineTreshold": 2})


def test_lease_contract_is_shared_not_duplicated():
    """The Lease wire contract (field names, apiVersion, the per-
    component lease names) must have ONE definition — cluster/lease.py —
    consumed everywhere else by import (the binding_of rule): the
    elector, the fenced client, the soaks, the dashboard's control-plane
    panel, and the manifests all coordinate through these strings, so a
    re-spelling in any of them silently breaks failover. Also checks
    the manifests' leader-election knobs render through to the
    controller CLI (a rendered flag argparse does not define is a
    silently ignored deployment knob)."""
    import subprocess

    from kubeflow_tpu.cluster import lease as L

    assert L.LEASE_API_VERSION == "coordination.k8s.io/v1"
    assert L.HOLDER_FIELD == "holderIdentity"
    assert L.TRANSITIONS_FIELD == "leaseTransitions"

    pkg = os.path.join(REPO_ROOT, "kubeflow_tpu")
    lease_py = os.path.join("cluster", "lease.py")
    for literal in (L.HOLDER_FIELD, L.ACQUIRE_TIME_FIELD,
                    L.RENEW_TIME_FIELD, L.DURATION_FIELD,
                    L.TRANSITIONS_FIELD, L.LEASE_API_VERSION):
        hits = subprocess.run(
            ["grep", "-rl", f'"{literal}"', pkg],
            capture_output=True, text=True).stdout.split()
        assert [os.path.relpath(h, pkg) for h in hits] == [lease_py], \
            f"{literal!r} defined outside cluster/lease.py: {hits}"

    def src(*rel):
        with open(os.path.join(pkg, *rel)) as f:
            return f.read()

    # the consumers import, never re-spell
    assert "lease_record" in src("webapps", "dashboard.py")
    assert "LeaderElector" in src("controllers", "__main__.py")
    # the production write path is FENCED, not just pop-gated: a
    # deposed leader's in-flight reconcile must die at the client
    # boundary (docs/operations.md "Control-plane HA")
    assert "FencedKubeClient" in src("controllers", "__main__.py")
    for name in ("OPERATOR_LEASE", "SCHEDULER_LEASE"):
        assert name in src("manifests", "training.py"), \
            f"manifests must render the shared {name} constant"

    # manifests → CLI plumbing: the rendered flags must exist in the
    # controller argparse, and the HA shape must actually render
    from kubeflow_tpu.manifests.training import (tpu_job_operator,
                                                 tpu_scheduler)
    for component, lease_name in ((tpu_job_operator, L.OPERATOR_LEASE),
                                  (tpu_scheduler, L.SCHEDULER_LEASE)):
        objs = component()
        dep = next(o for o in objs if o["kind"] == "Deployment")
        args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--leader-elect" in args
        assert f"--lease-name={lease_name}" in args
        assert dep["spec"]["replicas"] == 2, \
            "leader election exists to run replicas: 2"
        lease_roles = [o for o in objs if o["kind"] == "Role"
                       and any("leases" in r.get("resources", [])
                               for r in o.get("rules", []))]
        assert lease_roles, "leases RBAC must ride the HA deployment"
        # opting out drops back to a single replica — two un-elected
        # replicas would double-drive every gang
        solo = next(o for o in component(leader_elect=False)
                    if o["kind"] == "Deployment")
        solo_args = solo["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--leader-elect" not in solo_args
        assert solo["spec"]["replicas"] == 1
    main_src = src("controllers", "__main__.py")
    for flag in ("--leader-elect", "--lease-name", "--lease-namespace",
                 "--lease-duration", "--identity"):
        assert flag in main_src, \
            f"controllers/__main__.py must define {flag}"


def test_badput_categories_defined_once_and_shared():
    """The goodput/badput category vocabulary must have ONE definition
    (obs/goodput.py) consumed by the ledger, the sim, the dashboard,
    the operator's final-ledger export, and the bench alike — the
    binding_of rule: sim arms and the real cluster must report
    COMPARABLE decompositions, so a category-name drift between them
    silently breaks every cross-table read."""
    import subprocess

    from kubeflow_tpu.obs.goodput import (BADPUT_CATEGORIES,
                                          BADPUT_OTHER, decompose)

    assert BADPUT_CATEGORIES == (
        "queue_wait", "startup", "compile", "checkpoint",
        "restart_recompute", "rollback_recompute", "resize", "stall",
        "pipeline_bubble", "other")

    # single definition: the distinctive category literals appear as
    # quoted strings in exactly one source file — every other layer
    # imports the names (common-word categories like "compile" would
    # false-positive a grep, so the check pins the unambiguous ones;
    # "pipeline_bubble" is the ISSUE 15 MPMD schedule-idle category —
    # the worker emits SPAN_PIPELINE_BUBBLE spans, never re-spells it;
    # "rollback_recompute" is the ISSUE 17 sentinel LKG-rollback
    # category — replayed steps inside an anomaly's (lkg, trip] range)
    pkg = os.path.join(REPO_ROOT, "kubeflow_tpu")
    for literal in ("queue_wait", "restart_recompute",
                    "rollback_recompute", "pipeline_bubble"):
        hits = subprocess.run(
            ["grep", "-rl", f'"{literal}"', pkg],
            capture_output=True, text=True).stdout.split()
        assert [os.path.relpath(h, pkg) for h in hits] == \
            [os.path.join("obs", "goodput.py")], \
            f"{literal!r} defined outside obs/goodput.py: {hits}"

    def src(*rel):
        with open(os.path.join(REPO_ROOT, *rel)) as f:
            return f.read()

    # the consumers go through the shared module, not re-spelled names
    sim_src = src("kubeflow_tpu", "scheduler", "sim.py")
    for use in ("from ..obs import goodput as gp", "gp.BADPUT_QUEUE_WAIT",
                "gp.BADPUT_CATEGORIES"):
        assert use in sim_src, f"scheduler/sim.py must consume {use}"
    dash_src = src("kubeflow_tpu", "webapps", "dashboard.py")
    assert "from ..obs.goodput import" in dash_src
    ctrl_src = src("kubeflow_tpu", "controllers", "tpujob.py")
    for use in ("export_job_ledger", "ledger_for", "GOODPUT_ANNOTATION"):
        assert use in ctrl_src, \
            f"controllers/tpujob.py must consume {use}"
    bench_src = src("bench.py")
    assert "gp.BADPUT_CATEGORIES" in bench_src

    # every ledger reports the FULL vocabulary (zeros, not omissions) —
    # tables line up column-for-column across surfaces
    led = decompose([])
    assert set(led["badputSeconds"]) == set(BADPUT_CATEGORIES)
    assert BADPUT_OTHER in led["badputSeconds"]

    # ...and the sim's table does too
    from kubeflow_tpu.scheduler.sim import make_workload, simulate
    row = simulate(make_workload(0, n_jobs=4), pools=("v5e-16",),
                   policy="fifo")
    assert set(row["goodput"]["badput"]) == set(BADPUT_CATEGORIES)


def test_serving_badput_categories_defined_once_and_shared():
    """The SERVING badput vocabulary (ISSUE 11) follows the same
    single-definition rule as the training one: defined in
    obs/goodput.py, imported by the request tracer, the replica
    registry, the dashboard rollup, and the bench — a category-name
    drift between the model server's ledger and the dashboard's table
    would silently break every cross-surface read."""
    import subprocess

    from kubeflow_tpu.obs.goodput import (BADPUT_OTHER,
                                          SERVING_BADPUT_CATEGORIES,
                                          decompose_request)

    assert SERVING_BADPUT_CATEGORIES == (
        "queue", "batch_form", "pad_waste", "h2d", "respond", "other")

    # single definition: the distinctive literals appear as quoted
    # strings in exactly one source file (common words like "queue"
    # and "device" would false-positive; the span NAMES use hyphenated
    # forms — "batch-form" — so the snake_case categories are exact)
    pkg = os.path.join(REPO_ROOT, "kubeflow_tpu")
    for literal in ("batch_form", "pad_waste"):
        hits = subprocess.run(
            ["grep", "-rl", f'"{literal}"', pkg],
            capture_output=True, text=True).stdout.split()
        assert [os.path.relpath(h, pkg) for h in hits] == \
            [os.path.join("obs", "goodput.py")], \
            f"{literal!r} defined outside obs/goodput.py: {hits}"

    def src(*rel):
        with open(os.path.join(REPO_ROOT, *rel)) as f:
            return f.read()

    # consumers go through the shared module, never re-spelled names
    tracer_src = src("kubeflow_tpu", "serving", "request_trace.py")
    for use in ("from ..obs import goodput as gp",
                "gp.SERVING_DEVICE", "gp.SERVING_PAD_WASTE",
                "gp.SERVING_REQUEST_SPAN"):
        assert use in tracer_src, \
            f"serving/request_trace.py must consume {use}"
    replica_src = src("kubeflow_tpu", "serving", "replica_state.py")
    assert "gp.SERVING_BADPUT_CATEGORIES" in replica_src
    dash_src = src("kubeflow_tpu", "webapps", "dashboard.py")
    assert "from ..obs.goodput import serving_rollup" in dash_src
    bench_src = src("bench.py")
    assert "gp.SERVING_BADPUT_CATEGORIES" in bench_src

    # every request ledger reports the FULL vocabulary (zeros, not
    # omissions) so tables line up column-for-column across surfaces
    led = decompose_request(1.0, {})
    assert set(led["badputSeconds"]) == set(SERVING_BADPUT_CATEGORIES)
    assert BADPUT_OTHER in led["badputSeconds"]


def test_fleet_badput_categories_defined_once_and_shared():
    """The FLEET badput vocabulary (ISSUE 12: retry / hedge_waste)
    follows the same single-definition rule: defined in
    obs/goodput.py, consumed by the fleet router, the soak's audit,
    the dashboard rollup, and the bench through the shared module —
    never re-spelled."""
    import subprocess

    from kubeflow_tpu.obs.goodput import (BADPUT_OTHER,
                                          FLEET_BADPUT_CATEGORIES,
                                          decompose_fleet_request,
                                          fleet_sum_ok)

    assert FLEET_BADPUT_CATEGORIES == ("retry", "hedge_waste", "other")

    # single definition: the distinctive literal appears as a quoted
    # string in exactly one source file ("retry" is too common a word
    # to grep; "hedge_waste" is the fingerprint)
    pkg = os.path.join(REPO_ROOT, "kubeflow_tpu")
    hits = subprocess.run(
        ["grep", "-rl", '"hedge_waste"', pkg],
        capture_output=True, text=True).stdout.split()
    assert [os.path.relpath(h, pkg) for h in hits] == \
        [os.path.join("obs", "goodput.py")], \
        f'"hedge_waste" defined outside obs/goodput.py: {hits}'

    def src(*rel):
        with open(os.path.join(REPO_ROOT, *rel)) as f:
            return f.read()

    fleet_src = src("kubeflow_tpu", "serving", "fleet.py")
    for use in ("gp.decompose_fleet_request", "gp.FLEET_REQUEST_SPAN"):
        assert use in fleet_src, f"serving/fleet.py must consume {use}"
    chaos_src = src("kubeflow_tpu", "cluster", "chaos.py")
    assert "gp.fleet_sum_ok" in chaos_src
    assert "gp.SERVING_HEDGE_WASTE" in chaos_src
    dash_src = src("kubeflow_tpu", "webapps", "dashboard.py")
    assert "from ..obs.goodput import fleet_rollup" in dash_src
    bench_src = src("bench.py")
    assert "gp.FLEET_BADPUT_CATEGORIES" in bench_src

    # the full vocabulary on every fleet ledger, and the wall-partition
    # check holds on a fresh decomposition by construction
    led = decompose_fleet_request(1.0, 0.6, 0.3, 0.2)
    assert set(led["badputSeconds"]) == set(FLEET_BADPUT_CATEGORIES)
    assert BADPUT_OTHER in led["badputSeconds"]
    assert fleet_sum_ok(led)


def test_collective_vocabulary_defined_once_and_shared():
    """The HLO collective-op vocabulary has ONE definition
    (obs/collectives.py, ISSUE 13): the comm analyzer, bench.py's
    collective_counts, the dryrun, and the weight-update tests all
    consume that module, so the bench and the analyzer can never drift
    on which op literals they count (the obs/goodput.py
    single-definition rule applied to HLO opcodes)."""
    import subprocess

    from kubeflow_tpu.obs.collectives import (ASYNC_START_FORMS,
                                              COLLECTIVE_OPS)

    assert COLLECTIVE_OPS == ("all-reduce", "reduce-scatter",
                              "all-gather", "all-to-all",
                              "collective-permute")
    assert ASYNC_START_FORMS == tuple(f"{op}-start"
                                      for op in COLLECTIVE_OPS)

    # single definition: the unambiguous parser literals (the async
    # -start forms never appear in prose/docstrings) live in exactly
    # one source file across the package, the bench, and the dryrun
    for literal in ("all-reduce-start", "all-gather-start",
                    "reduce-scatter-start"):
        hits = subprocess.run(
            ["grep", "-rl", literal,
             os.path.join(REPO_ROOT, "kubeflow_tpu"),
             os.path.join(REPO_ROOT, "bench.py"),
             os.path.join(REPO_ROOT, "__graft_entry__.py")],
            capture_output=True, text=True).stdout.split()
        hits = [h for h in hits if "__pycache__" not in h]
        assert [os.path.relpath(h, REPO_ROOT) for h in hits] == \
            [os.path.join("kubeflow_tpu", "obs", "collectives.py")], \
            f"{literal!r} defined outside obs/collectives.py: {hits}"

    def src(*rel):
        with open(os.path.join(REPO_ROOT, *rel)) as f:
            return f.read()

    # bench consumes the shared vocabulary instead of re-spelling the
    # counting regex (collective_counts moved out of bench in ISSUE 13)
    bench_src = src("bench.py")
    assert "from kubeflow_tpu.obs.collectives import collective_counts" \
        in bench_src
    assert "def collective_counts" not in bench_src
    # ... and the quoted hyphenated opcodes never reappear in bench
    for literal in ('"reduce-scatter"', '"all-gather"', '"all-reduce"'):
        assert literal not in bench_src, \
            f"bench.py re-spells {literal}; import from obs/collectives"
    # the dryrun's comm verdict and the worker's profile go through the
    # analyzer, not a private parser
    entry_src = src("__graft_entry__.py")
    assert "from kubeflow_tpu.obs.collectives import" in entry_src
    worker_src = src("kubeflow_tpu", "runtime", "worker.py")
    for use in ("analyze_hlo", "export_comm_metrics", "slice_assignment",
                "COMM_PROFILE_SPAN"):
        assert use in worker_src, f"runtime/worker.py must consume {use}"


def test_serving_resilience_knobs_are_plumbed_end_to_end():
    """The drain/fleet knobs must exist in EVERY layer at once
    (ISSUE 12): the serving manifest renders probes + preStop + PDB +
    --drain-timeout, the server CLI parses --drain-timeout into
    ModelServer.drain_timeout_s, the drain contract fields ride the
    healthz payload, and the retry/deadline headers are defined once in
    request_trace.py and consumed (never re-spelled) by the server, the
    fleet router, and the client."""
    from kubeflow_tpu.manifests.serving import tpu_serving

    objs = tpu_serving(num_replicas=3, drain_timeout_s=9.0)
    dep = next(o for o in objs if o["kind"] == "Deployment")
    container = dep["spec"]["template"]["spec"]["containers"][0]
    assert "--drain-timeout=9.0" in container["args"]
    assert container["readinessProbe"]["httpGet"]["path"] == "/healthz"
    assert container["livenessProbe"]["httpGet"]["path"] == \
        "/healthz?live=1"
    assert container["lifecycle"]["preStop"]["httpGet"]["path"] == \
        "/drain"
    assert any(o["kind"] == "PodDisruptionBudget" for o in objs)

    def src(*rel):
        with open(os.path.join(REPO_ROOT, *rel)) as f:
            return f.read()

    http_src = src("kubeflow_tpu", "serving", "http_server.py")
    assert "--drain-timeout" in http_src
    assert "drain_timeout_s=args.drain_timeout" in http_src

    # the deadline/request-id headers: one definition, shared consumers
    trace_src = src("kubeflow_tpu", "serving", "request_trace.py")
    assert 'DEADLINE_HEADER = "x-request-deadline"' in trace_src
    for consumer in ("http_server.py", "fleet.py", "client.py"):
        csrc = src("kubeflow_tpu", "serving", consumer)
        assert "DEADLINE_HEADER" in csrc, \
            f"serving/{consumer} must consume DEADLINE_HEADER"
        assert '"x-request-deadline"' not in csrc, \
            f"serving/{consumer} re-spells the deadline header"

    # the draining/uptime healthz fields the router polls exist on the
    # snapshot, and the fleet reads exactly those names
    from kubeflow_tpu.obs.registry import Registry
    from kubeflow_tpu.serving.replica_state import ReplicaState
    snap = ReplicaState(Registry()).snapshot()
    assert "draining" in snap and "uptimeSeconds" in snap
    fleet_src = src("kubeflow_tpu", "serving", "fleet.py")
    assert 'snap.get("draining")' in fleet_src
    assert 'snap.get("uptimeSeconds")' in fleet_src


def test_serving_batching_and_autoscaler_knobs_are_plumbed_end_to_end():
    """The ISSUE 18 knobs must exist in EVERY layer at once: the
    serving manifest renders ``--batching`` and (with autoscale=True) a
    ServingFleet whose ``spec.autoscaler`` keys the reconciler's
    AutoscalerConfig accepts verbatim; the server CLI parses
    ``--batching`` into the MicroBatcher; and the autoscaler controller
    is registered so the rendered object has a consumer."""
    from kubeflow_tpu.controllers.autoscaler import (AutoscalerConfig,
                                                     ServingFleetReconciler)
    from kubeflow_tpu.manifests.serving import tpu_serving

    objs = tpu_serving(batching="window", autoscale=True,
                       autoscale_min=2, autoscale_max=6)
    dep = next(o for o in objs if o["kind"] == "Deployment")
    container = dep["spec"]["template"]["spec"]["containers"][0]
    assert "--batching=window" in container["args"]

    fleet = next(o for o in objs if o["kind"] == "ServingFleet")
    knobs = fleet["spec"]["autoscaler"]
    # every rendered knob is one the reconciler's config accepts — a
    # renamed key on either side fails loudly here, not silently at
    # reconcile time
    cfg = AutoscalerConfig.from_dict(knobs)
    assert cfg.min_replicas == 2 and cfg.max_replicas == 6
    assert set(knobs) <= set(AutoscalerConfig.KEYS)

    # unscaled renders carry no ServingFleet
    assert not any(o["kind"] == "ServingFleet" for o in tpu_serving())

    def src(*rel):
        with open(os.path.join(REPO_ROOT, *rel)) as f:
            return f.read()

    # the CLI parses --batching and hands it to the batcher layer
    http_src = src("kubeflow_tpu", "serving", "http_server.py")
    assert "--batching" in http_src
    assert "batching=args.batching" in http_src
    batcher_src = src("kubeflow_tpu", "serving", "batcher.py")
    assert "BATCHING_MODES" in batcher_src

    # the rendered ServingFleet has a registered consumer
    from kubeflow_tpu.controllers.__main__ import (CONTROLLER_FACTORIES,
                                                   _register_defaults)
    _register_defaults()
    assert CONTROLLER_FACTORIES["autoscaler"] is ServingFleetReconciler
    assert ServingFleetReconciler.primary[1] == fleet["kind"]


def test_run_policy_fields_are_plumbed_end_to_end():
    """Every RunPolicy field must be plumbed spec → controller →
    manifests: round-trip through the TPUJob spec wire format
    (api/trainingjob.py), consumed by the reconciler
    (controllers/tpujob.py), and renderable from the example manifest
    builder (manifests/training.py tpu-job-simple) — so a future
    failure-handling knob (the backoffLimit / stallTimeoutSeconds
    family) can't silently exist in one layer only."""
    import dataclasses

    from kubeflow_tpu.api.trainingjob import RunPolicy, TrainingJob
    from kubeflow_tpu.manifests.training import tpu_job_simple

    non_default = {
        "clean_pod_policy": "None",
        "backoff_limit": 7,
        "active_deadline_seconds": 1234,
        "gang_scheduling": True,    # mandatory for TPU replicas
        "ttl_seconds_after_finished": 55,
        "restart_backoff_seconds": 11.0,
        "restart_backoff_max_seconds": 222.0,
        "stall_timeout_seconds": 77,
        "max_anomaly_rollbacks": 5,
    }
    fields = {f.name for f in dataclasses.fields(RunPolicy)}
    assert fields == set(non_default), \
        "RunPolicy field added/removed — extend this plumbing check"

    # spec wire round-trip: to_dict → from_manifest → identical policy
    rp = RunPolicy(**non_default)
    manifest = {
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "t", "namespace": "ns"},
        "spec": {"replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [{"name": "c"}]}}}},
            "runPolicy": rp.to_dict()},
    }
    assert TrainingJob.from_manifest(manifest).run_policy == rp

    # controller: every field is read off run_policy somewhere in the
    # reconciler (gang_scheduling excepted: TPU gangs ALWAYS carry the
    # pod-group label, the knob only parameterizes the operator deploy)
    with open(os.path.join(REPO_ROOT, "kubeflow_tpu", "controllers",
                           "tpujob.py")) as f:
        controller_src = f.read()
    for name in fields - {"gang_scheduling"}:
        assert (f"run_policy.{name}" in controller_src
                or f"rp.{name}" in controller_src), \
            f"RunPolicy.{name} is never consumed by controllers/tpujob.py"

    # manifests: the example builder accepts each knob and renders the
    # policy through RunPolicy.to_dict (admissible end to end)
    job = next(o for o in tpu_job_simple(**{k: v for k, v in
                                            non_default.items()})
               if o["kind"] == "TPUJob")
    assert job["spec"]["runPolicy"] == rp.to_dict()
    assert TrainingJob.from_manifest(job).run_policy == rp


def test_integrity_knobs_are_plumbed_end_to_end():
    """Every IntegritySpec field (ISSUE 17 ``spec.integrity``) must be
    representable end-to-end, the InputSpec rule: parsed+serialized
    through the TPUJob spec (api/trainingjob.py), rendered into worker
    env by the controller via to_env, consumed by the worker's
    train()/CLI surface, and named in the manifests CRD schema +
    example builder — so a sentinel knob can't silently exist in one
    layer only."""
    import dataclasses
    import inspect

    import pytest

    from kubeflow_tpu.api.trainingjob import IntegritySpec, TrainingJob
    from kubeflow_tpu.manifests.training import tpu_job_simple
    from kubeflow_tpu.runtime import worker

    def src(*rel):
        with open(os.path.join(REPO_ROOT, "kubeflow_tpu", *rel)) as f:
            return f.read()

    knobs = dataclasses.fields(IntegritySpec)
    assert {k.name for k in knobs} == {
        "enabled", "spike_z", "window_steps", "check_every_steps"}
    worker_src = src("runtime", "worker.py")
    controller_src = src("controllers", "tpujob.py")
    manifests_src = src("manifests", "training.py")
    train_params = inspect.signature(worker.train).parameters
    for knob in knobs:
        # worker: a CLI flag and the env fallback
        assert knob.metadata["cli"] in worker_src, knob.name
        assert knob.metadata["env"] in worker_src, knob.name
        # controller: rendered into worker env through the one shared
        # serializer (env names asserted against the worker above)
        assert "job.integrity.to_env()" in controller_src
        # manifests: the CRD schema names the spec field
        assert f'"{knob.metadata["spec_field"]}"' in manifests_src, \
            knob.name
    # train() consumes the knobs by their canonical kwarg names
    for kwarg in ("integrity", "integrity_spike_z", "integrity_window",
                  "integrity_check_every"):
        assert kwarg in train_params, kwarg

    # spec wire round-trip: to_dict → from_manifest → identical spec,
    # and the controller env render matches the declared names
    ispec = IntegritySpec(enabled=True, spike_z=6.0, window_steps=16,
                          check_every_steps=5)
    manifest = {
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "t", "namespace": "ns"},
        "spec": {"replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [{"name": "c"}]}}}},
            "integrity": ispec.to_dict()},
    }
    job = TrainingJob.from_manifest(manifest)
    assert job.integrity == ispec
    assert job.to_manifest()["spec"]["integrity"] == ispec.to_dict()
    assert ispec.to_env() == {
        "KFTPU_INTEGRITY": "1", "KFTPU_INTEGRITY_SPIKE_Z": "6.0",
        "KFTPU_INTEGRITY_WINDOW": "16",
        "KFTPU_INTEGRITY_CHECK_EVERY": "5"}

    # admission rejects garbage (a typo'd knob must fail at apply), and
    # tuning knobs without enabled: true are a hard error, not a silent
    # unarmed sentinel
    with pytest.raises(ValueError, match="spikeZ"):
        IntegritySpec.from_dict({"enabled": True, "spikeZ": 0})
    with pytest.raises(ValueError, match="unknown"):
        IntegritySpec.from_dict({"spike_z": 4.0})
    with pytest.raises(ValueError, match="mapping"):
        IntegritySpec.from_dict([True])   # YAML list typo
    with pytest.raises(ValueError, match="enabled"):
        IntegritySpec.from_dict({"windowSteps": 8})

    # example builder renders the block end to end
    ex = next(o for o in tpu_job_simple(
        integrity=True, integrity_spike_z=6.0,
        integrity_window_steps=16, integrity_check_every_steps=5)
        if o["kind"] == "TPUJob")
    assert ex["spec"]["integrity"] == ispec.to_dict()
    assert TrainingJob.from_manifest(ex).integrity == ispec


def test_anomaly_event_literals_defined_once_and_shared():
    """The sentinel's event vocabulary must have ONE definition each —
    the badput-categories rule applied to ISSUE 17: the ``anomaly``
    span literal lives in obs/goodput.py (SPAN_ANOMALY) and the
    ``numeric-anomaly`` health-event literal in scheduler/health.py
    (EVENT_NUMERIC_ANOMALY); every emitter/consumer imports the name.
    A re-spelled literal would silently decouple the worker's trip
    from the ledger's rollback_recompute split or the host blame."""
    import subprocess

    from kubeflow_tpu.obs.goodput import SPAN_ANOMALY
    from kubeflow_tpu.scheduler import health

    assert SPAN_ANOMALY == "anomaly"
    assert health.EVENT_NUMERIC_ANOMALY == "numeric-anomaly"

    pkg = os.path.join(REPO_ROOT, "kubeflow_tpu")

    def griep(pattern):
        hits = subprocess.run(
            ["grep", "-rl", "--include=*.py", pattern, pkg],
            capture_output=True, text=True).stdout.split()
        return sorted(os.path.relpath(h, pkg) for h in hits)

    # single definition sites (assignment form, not mere mention)
    assert griep("SPAN_ANOMALY = ") == [os.path.join("obs", "goodput.py")]
    assert griep("EVENT_NUMERIC_ANOMALY = ") == \
        [os.path.join("scheduler", "health.py")]
    assert griep('"numeric-anomaly"') == \
        [os.path.join("scheduler", "health.py")]
    # no emitter re-spells the span name into the tracer
    assert griep('event("anomaly"') == []

    def src(*rel):
        with open(os.path.join(REPO_ROOT, "kubeflow_tpu", *rel)) as f:
            return f.read()

    # consumers import the shared names
    assert "SPAN_ANOMALY" in src("runtime", "worker.py")
    assert "SPAN_ANOMALY" in src("webapps", "dashboard.py")
    assert "EVENT_NUMERIC_ANOMALY" in src("controllers", "tpujob.py")
    # and the ledger's rollback split keys off the shared span name
    assert "SPAN_ANOMALY" in src("obs", "goodput.py")


def test_experiment_contract_is_plumbed_end_to_end():
    """The hyperparameter-search wire contract has ONE definition per
    literal, all in api/experiment.py: the default objective metric
    (``DEFAULT_OBJECTIVE_METRIC``), the per-window objective span name
    (``SPAN_OBJECTIVE``) and the out-of-band observation annotation
    (``OBSERVATION_ANNOTATION``). The worker's span emitter, the
    Experiment reconciler's median-stopping read, the StudyJob compat
    parser and the bench harness all import the names — a re-spelled
    ``"loss"`` would silently decouple what the worker reports from
    what the reconciler ranks trials by."""
    import subprocess

    import pytest

    from kubeflow_tpu.api.experiment import (DEFAULT_OBJECTIVE_METRIC,
                                             OBSERVATION_ANNOTATION,
                                             SPAN_OBJECTIVE, Experiment)

    assert DEFAULT_OBJECTIVE_METRIC == "loss"
    assert SPAN_OBJECTIVE == "objective"
    assert OBSERVATION_ANNOTATION == "kubeflow.org/observation"

    pkg = os.path.join(REPO_ROOT, "kubeflow_tpu")

    def griep(pattern):
        hits = subprocess.run(
            ["grep", "-rl", "--include=*.py", pattern, pkg],
            capture_output=True, text=True).stdout.split()
        return sorted(os.path.relpath(h, pkg) for h in hits)

    # single definition sites (assignment form, not mere mention)
    assert griep("DEFAULT_OBJECTIVE_METRIC = ") == \
        [os.path.join("api", "experiment.py")]
    assert griep("SPAN_OBJECTIVE = ") == \
        [os.path.join("api", "experiment.py")]
    assert griep("OBSERVATION_ANNOTATION = ") == \
        [os.path.join("api", "experiment.py")]
    assert griep('"kubeflow.org/observation"') == \
        [os.path.join("api", "experiment.py")]

    def src(*rel):
        with open(os.path.join(REPO_ROOT, "kubeflow_tpu", *rel)) as f:
            return f.read()

    # the experiment layers never re-spell the default metric literal
    for rel in (("controllers", "experiment.py"),
                ("katib", "studyjob.py")):
        assert '"loss"' not in src(*rel), os.path.join(*rel)
    # consumers import the shared names
    assert "SPAN_OBJECTIVE" in src("runtime", "worker.py")
    assert "SPAN_OBJECTIVE" in src("controllers", "experiment.py")
    assert "OBSERVATION_ANNOTATION" in src("controllers", "experiment.py")
    assert "OBSERVATION_ANNOTATION" in src("katib", "studyjob.py")
    assert "DEFAULT_OBJECTIVE_METRIC" in src("katib", "studyjob.py")
    # manifests: the Experiment CRD schema names every spec block
    manifests_src = src("manifests", "katib.py")
    for spec_field in ("objective", "algorithm", "parameters",
                       "maxTrials", "parallelism", "earlyStopping",
                       "trialTemplate"):
        assert f'"{spec_field}"' in manifests_src, spec_field
    # dashboard: the rollup surface carries the shared field names
    dash_src = src("webapps", "dashboard.py")
    for key in ("objectiveMetric", "warmStartFraction", "stoppedEarly"):
        assert f'"{key}"' in dash_src, key

    # spec wire round-trip: objective.metric unset → the shared default,
    # and the default survives to_manifest → from_manifest unchanged
    template = {
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "t"},
        "spec": {"replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [{"name": "c"}]}}}}},
    }
    manifest = {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "Experiment",
        "metadata": {"name": "e", "namespace": "ns"},
        "spec": {"parameters": [{"name": "--lr", "type": "double",
                                 "min": 0.1, "max": 0.9}],
                 "maxTrials": 2, "trialTemplate": template},
    }
    exp = Experiment.from_manifest(manifest)
    assert exp.objective_metric == DEFAULT_OBJECTIVE_METRIC
    rt = Experiment.from_manifest(exp.to_manifest())
    assert rt.objective_metric == DEFAULT_OBJECTIVE_METRIC
    assert exp.to_manifest()["spec"]["objective"]["metric"] == \
        DEFAULT_OBJECTIVE_METRIC
    # admission rejects garbage (a typo'd objective knob fails at apply)
    bad = dict(manifest)
    bad["spec"] = dict(manifest["spec"], objective={"metirc": "loss"})
    with pytest.raises(ValueError, match="unknown"):
        Experiment.from_manifest(bad)


class TestChecker:
    def _check(self, tmp_path, source, name="m.py"):
        p = tmp_path / name
        p.write_text(textwrap.dedent(source))
        return check_file(str(p))

    def test_unused_import_flagged(self, tmp_path):
        fs = self._check(tmp_path, """
            import os
            import sys
            print(sys.argv)
        """)
        assert [f.code for f in fs] == ["F401"]
        assert "'os'" in fs[0].message

    def test_dotted_and_aliased_imports(self, tmp_path):
        # urllib.error + urllib.request coexist (distinct keys, shared root)
        fs = self._check(tmp_path, """
            import urllib.error
            import urllib.request
            urllib.request.urlopen
        """)
        assert fs == []

    def test_same_scope_redefinition_flagged(self, tmp_path):
        fs = self._check(tmp_path, """
            import json
            import json
            json.dumps({})
        """)
        assert [f.code for f in fs] == ["F811"]

    def test_cross_function_locals_not_flagged(self, tmp_path):
        fs = self._check(tmp_path, """
            def a():
                import json
                return json.dumps({})

            def b():
                import json
                return json.loads("{}")
        """)
        assert fs == []

    def test_bare_except_flagged_noqa_suppresses(self, tmp_path):
        fs = self._check(tmp_path, """
            try:
                pass
            except:
                pass
            try:
                pass
            except:  # noqa
                pass
        """)
        assert [f.code for f in fs] == ["E722"]

    def test_syntax_error_reported(self, tmp_path):
        fs = self._check(tmp_path, "def broken(:\n")
        assert [f.code for f in fs] == ["E999"]

    def test_init_reexports_exempt(self, tmp_path):
        fs = self._check(tmp_path, "from os import path\n",
                         name="__init__.py")
        assert fs == []

    def test_all_counts_as_use(self, tmp_path):
        fs = self._check(tmp_path, """
            from os import path
            __all__ = ["path"]
        """)
        assert fs == []


def test_ctrl_telemetry_vocabulary_defined_once_and_shared():
    """The control-plane telemetry vocabulary (ISSUE 20) — verbs,
    reconcile-pass phases, relist reasons, the component header, the
    ctrl-pass trace prefix — must have ONE definition
    (obs/controlplane.py) consumed by the scheduler, both apiserver
    layers, the controller runtime, and the bench. The acceptance gate
    is EXACT client/server reconciliation: a verb or phase re-spelled
    in any consumer would silently fork the ledgers."""
    import subprocess

    from kubeflow_tpu.obs import controlplane as ctrlobs

    assert ctrlobs.VERBS == (
        "create", "get", "list", "update", "update_status", "patch",
        "delete", "watch")
    assert ctrlobs.MUTATING_VERBS == frozenset((
        "create", "update", "update_status", "patch", "delete"))
    assert ctrlobs.PHASES == (
        "snapshot", "health-pass", "plan", "writes", "warm-pass")
    assert ctrlobs.RELIST_REASONS == ("initial", "resync",
                                      "leader-gain")
    assert ctrlobs.COMPONENT_HEADER == "X-Kftpu-Component"

    # single definition: the distinctive literals appear as quoted
    # strings in exactly one source file — every other layer imports
    # the names (common words like "snapshot"/"plan"/"get" would
    # false-positive a grep, so the check pins the unambiguous ones:
    # the hyphenated phases, the leader-gain relist reason, the trace
    # prefix, and the attribution header)
    pkg = os.path.join(REPO_ROOT, "kubeflow_tpu")
    for literal in ("health-pass", "warm-pass", "leader-gain",
                    "ctrlpass-", "X-Kftpu-Component"):
        hits = subprocess.run(
            ["grep", "-rl", f'"{literal}"', pkg],
            capture_output=True, text=True).stdout.split()
        assert [os.path.relpath(h, pkg) for h in hits] == \
            [os.path.join("obs", "controlplane.py")], \
            f"{literal!r} defined outside obs/controlplane.py: {hits}"

    def src(*rel):
        with open(os.path.join(REPO_ROOT, *rel)) as f:
            return f.read()

    # the consumers go through the shared module, not re-spelled names
    sched_src = src("kubeflow_tpu", "scheduler", "core.py")
    for use in ("ctrlobs.PHASE_SNAPSHOT", "ctrlobs.PHASE_PLAN",
                "ctrlobs.PHASE_WRITES"):
        assert use in sched_src, f"scheduler/core.py must consume {use}"
    fake_src = src("kubeflow_tpu", "cluster", "fake.py")
    assert "ctrlobs.VERB_" in fake_src
    api_src = src("kubeflow_tpu", "cluster", "apiserver.py")
    for use in ("ctrlobs.COMPONENT_HEADER", "ctrlobs.VERB_",
                "ctrlobs.payload_bytes"):
        assert use in api_src, f"cluster/apiserver.py must consume {use}"
    rt_src = src("kubeflow_tpu", "controllers", "runtime.py")
    for use in ("ctrlobs.RELIST_INITIAL", "ctrlobs.RELIST_RESYNC",
                "ctrlobs.RELIST_LEADER_GAIN", "ctrl_pass"):
        assert use in rt_src, \
            f"controllers/runtime.py must consume {use}"
    bench_src = src("bench.py")
    for use in ("ctrlobs.CTRL_PASS_SPAN", "ctrlobs.audit_mismatches",
                "ctrlobs.pass_stats"):
        assert use in bench_src, f"bench.py must consume {use}"
