"""Pipeline parallelism: GPipe schedule numerics + grads + full train step
on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.api.trainingjob import ShardingSpec
from kubeflow_tpu.models import transformer as T
from kubeflow_tpu.parallel.mesh import build_mesh
from kubeflow_tpu.parallel.pipeline import pipeline_apply, stage_sharding_spec
from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

pytestmark = pytest.mark.compute  # JAX trace/compile tests: excluded from smoke tier


def _linear_blocks(rng, num_layers, dim):
    """Stacked tiny residual-linear blocks: params [L, dim, dim]."""
    w = 0.02 * jax.random.normal(rng, (num_layers, dim, dim), jnp.float32)
    return {"w": w}


def _block_fn(p, h):
    return h + jnp.tanh(h @ p["w"])


def _sequential(params, x):
    def body(h, p):
        return _block_fn(p, h), None
    out, _ = jax.lax.scan(body, x, params)
    return out


@pytest.mark.slow
class TestPipelineApply:
    def test_matches_sequential(self):
        mesh = build_mesh(ShardingSpec(data=2, pipeline=4))
        params = _linear_blocks(jax.random.PRNGKey(0), 8, 16)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))

        ref = _sequential(params, x)
        out = jax.jit(lambda p, x: pipeline_apply(
            _block_fn, p, x, mesh=mesh, num_microbatches=4))(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_single_microbatch_and_uneven_raises(self):
        mesh = build_mesh(ShardingSpec(data=2, pipeline=4))
        params = _linear_blocks(jax.random.PRNGKey(0), 4, 8)
        x = jnp.ones((6, 8))
        with pytest.raises(ValueError, match="not divisible"):
            pipeline_apply(_block_fn, params, x, mesh=mesh,
                           num_microbatches=4)
        with pytest.raises(ValueError, match="layers"):
            pipeline_apply(_block_fn, {"w": params["w"][:3]}, jnp.ones((4, 8)),
                           mesh=mesh, num_microbatches=2)

    def test_no_pipeline_axis_falls_back_to_scan(self):
        mesh = build_mesh(ShardingSpec(data=8))
        params = _linear_blocks(jax.random.PRNGKey(0), 4, 8)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))
        out = pipeline_apply(_block_fn, params, x, mesh=mesh,
                             num_microbatches=2)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_sequential(params, x)),
                                   rtol=1e-6)

    def test_grads_match_sequential(self):
        mesh = build_mesh(ShardingSpec(pipeline=4, data=2))
        params = _linear_blocks(jax.random.PRNGKey(0), 4, 8)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))

        def loss_pp(p):
            return jnp.sum(pipeline_apply(
                _block_fn, p, x, mesh=mesh, num_microbatches=4) ** 2)

        def loss_ref(p):
            return jnp.sum(_sequential(p, x) ** 2)

        g_pp = jax.jit(jax.grad(loss_pp))(params)
        g_ref = jax.grad(loss_ref)(params)
        np.testing.assert_allclose(np.asarray(g_pp["w"]),
                                   np.asarray(g_ref["w"]),
                                   rtol=1e-4, atol=1e-5)

    def test_sharded_params_placement(self):
        mesh = build_mesh(ShardingSpec(pipeline=4, data=2))
        params = _linear_blocks(jax.random.PRNGKey(0), 8, 16)
        sharded = jax.device_put(
            params, jax.tree.map(
                lambda l: jax.sharding.NamedSharding(
                    mesh, stage_sharding_spec(l.ndim)), params))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
        out = jax.jit(lambda p, x: pipeline_apply(
            _block_fn, p, x, mesh=mesh, num_microbatches=4))(sharded, x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_sequential(params, x)),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.slow
class TestPipelinedTransformer:
    def test_pipelined_lm_matches_plain_scan(self):
        cfg = T.TransformerConfig(vocab_size=64, num_layers=4, embed_dim=32,
                                  num_heads=2, head_dim=16, mlp_dim=64,
                                  max_seq_len=16)
        model = T.PipelinedTransformerLM(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 64)
        params = model.init(jax.random.PRNGKey(1), tokens)
        ref = model.apply(params, tokens)  # scan path, no mesh

        mesh = build_mesh(ShardingSpec(data=2, pipeline=4))
        out = jax.jit(lambda p, t: model.apply(
            p, t, mesh=mesh, num_microbatches=2))(params, tokens)
        # bf16 compute: the two schedules accumulate in different orders, so
        # agreement is bounded by bf16 eps (~8e-3 relative) per block.
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-2, atol=8e-2)

    def test_f32_schedule_is_exactly_transparent(self):
        """At f32 the GPipe schedule is numerically transparent (no bf16
        boundary-cast rounding): scan path and pipeline path agree to
        float tolerance for every microbatch count, and different
        microbatch counts agree with each other."""
        cfg = T.TransformerConfig(vocab_size=64, num_layers=4, embed_dim=32,
                                  num_heads=2, head_dim=16, mlp_dim=64,
                                  max_seq_len=16, dtype=jnp.float32)
        model = T.PipelinedTransformerLM(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
        params = model.init(jax.random.PRNGKey(1), tokens)
        plain = model.apply(params, tokens)  # scan path, no mesh
        mesh = build_mesh(ShardingSpec(data=4, pipeline=2))
        outs = []
        for micro in (2, 4, 8):
            piped = jax.jit(lambda p, t, m=micro: model.apply(
                p, t, mesh=mesh, num_microbatches=m))(params, tokens)
            np.testing.assert_allclose(np.asarray(piped), np.asarray(plain),
                                       rtol=2e-5, atol=2e-5)
            outs.append(np.asarray(piped))
        # the schedule must not change WHAT is computed, only when (each
        # microbatch count is a different XLA program, so float tolerance,
        # not bit equality)
        np.testing.assert_allclose(outs[0], outs[2], rtol=2e-5, atol=2e-5)

    def test_logical_axes_cover_stacked_tree(self):
        cfg = T.TransformerConfig.tiny()
        model = T.PipelinedTransformerLM(cfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        abstract = jax.eval_shape(
            lambda r: model.init(r, tokens), jax.random.PRNGKey(0))
        axes = T.pipelined_logical_axes(abstract)
        blocks = axes["blocks"]
        for leaf in jax.tree.leaves(
                blocks, is_leaf=lambda x: isinstance(x, tuple)):
            assert leaf[0] == "layers"

    def test_full_train_step_pp(self):
        mesh = build_mesh(ShardingSpec(data=2, pipeline=4))
        spec = T.pipelined_workload_spec(
            cfg=T.TransformerConfig(vocab_size=64, num_layers=4, embed_dim=32,
                                    num_heads=2, head_dim=16, mlp_dim=64,
                                    max_seq_len=16),
            seq_len=16, mesh=mesh, num_microbatches=2)
        builder = TrainStepBuilder(
            mesh=mesh, loss_fn=spec.loss_fn, optimizer=optax.adamw(1e-3),
            rules=spec.rules, param_logical_axes=spec.param_logical_axes)
        state = builder.init(spec.init_fn, jax.random.PRNGKey(0))
        # stacked block params actually sharded over the pipeline axis
        qkv_sh = state.params["blocks"]["attn"]["qkv"]["kernel"].sharding
        assert "pipeline" in (qkv_sh.spec[0] or ())

        step = builder.build()
        batch = builder.place_batch(spec.batch_fn(jax.random.PRNGKey(1), 8))
        s1, m1 = step(state, batch)
        s2, m2 = step(s1, batch)
        assert np.isfinite(float(m1["loss"])) and np.isfinite(float(m2["loss"]))
        assert float(m2["loss"]) < float(m1["loss"]) + 1.0
