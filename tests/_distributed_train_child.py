"""Child for the two-process TRAIN test: the full worker loop
(TrainStepBuilder init/place_batch/step) on a multi-process mesh — the
scale-out path a real TPUJob gang runs, not just a bare psum."""

import json
import os
import sys

os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=4")


def main() -> int:
    from kubeflow_tpu.runtime.bootstrap import initialize
    from kubeflow_tpu.runtime.worker import train

    ctx = initialize()
    r = train(workload="transformer", steps=3, global_batch=16,
              sync_every=1, ctx=ctx, workload_kwargs={}, seed=4,
              handle_sigterm=False)
    print(json.dumps({"process_id": ctx.process_id,
                      "num_processes": ctx.num_processes,
                      "steps": r.steps,
                      "loss": r.final_metrics["loss"],
                      "grad_norm": r.final_metrics["grad_norm"]}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
