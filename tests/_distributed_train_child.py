"""Child for the two-process TRAIN test: the full worker loop
(TrainStepBuilder init/place_batch/step) on a multi-process mesh — the
scale-out path a real TPUJob gang runs, not just a bare psum.

Also the vehicle for the PREEMPTION test (tests/test_chaos.py): with
KFTPU_CHILD_SIGTERM=1 the child installs the PreemptionGuard, checkpoints
to KFTPU_CHILD_CKPT, and exits with the worker's restart-eligible
PREEMPTED_EXIT_CODE when a SIGTERM lands mid-train — exactly what a pod
sees when its TPU slice is reclaimed."""

import json
import os
import sys

os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=4")


def main() -> int:
    from kubeflow_tpu.runtime.bootstrap import initialize
    from kubeflow_tpu.runtime.worker import PREEMPTED_EXIT_CODE, train

    steps = int(os.environ.get("KFTPU_CHILD_STEPS", "3"))
    ckpt_dir = os.environ.get("KFTPU_CHILD_CKPT") or None
    ckpt_every = int(os.environ.get("KFTPU_CHILD_CKPT_EVERY", "100"))
    handle_sigterm = os.environ.get("KFTPU_CHILD_SIGTERM") == "1"

    ctx = initialize()
    r = train(workload="transformer", steps=steps, global_batch=16,
              sync_every=1, ctx=ctx, workload_kwargs={}, seed=4,
              checkpoint_dir=ckpt_dir, checkpoint_every=ckpt_every,
              handle_sigterm=handle_sigterm)
    print(json.dumps({"process_id": ctx.process_id,
                      "num_processes": ctx.num_processes,
                      "steps": r.steps,
                      "preempted": r.preempted,
                      "loss": r.final_metrics["loss"],
                      "grad_norm": r.final_metrics["grad_norm"]}),
          flush=True)
    # the worker main()'s exit contract: non-zero so the operator counts
    # the pod Failed (restart-eligible), EX_TEMPFAIL so logs read it as
    # "preempted, checkpointed, restart me" rather than a crash
    return PREEMPTED_EXIT_CODE if r.preempted else 0


if __name__ == "__main__":
    sys.exit(main())
