"""Child process for the two-process jax.distributed test.

Run with the KFTPU_* contract env rendered the way the TPUJob operator
renders it (api/topology.render_contracts); exercises the DISTRIBUTED
branch of runtime/bootstrap.initialize — jax.distributed.initialize over a
local coordinator — then one cross-process psum-shaped reduction through a
sharded global array on the contract's mesh.

Prints one JSON line: {"process_id": N, "global_devices": N, "local":
N, "sum": N, "mesh": {...}} — the parent asserts on it.
"""

import json
import os
import sys

# 4 local CPU devices per process -> 8 global over 2 processes (v5e-8's
# 2-host gang shape)
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=4")


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubeflow_tpu.runtime.bootstrap import initialize

    ctx = initialize()  # consumes the rendered KFTPU_* env
    mesh = ctx.mesh

    # one global data-parallel array: each process contributes its local
    # shard (value = global device index), then an all-reduce-shaped sum
    # runs across processes through XLA collectives
    sharding = NamedSharding(mesh, P("data"))
    n = ctx.num_processes * jax.local_device_count()

    def shard_value(index):
        # index is a tuple of slices into the global (n,) shape
        start = index[0].start or 0
        return jnp.arange(start, (index[0].stop or n), dtype=jnp.float32)

    arr = jax.make_array_from_callback((n,), sharding, shard_value)
    total = jax.jit(lambda x: jnp.sum(x), out_shardings=None)(arr)
    print(json.dumps({
        "process_id": ctx.process_id,
        "num_processes": ctx.num_processes,
        "global_devices": len(jax.devices()),
        "local_devices": jax.local_device_count(),
        "sum": float(total),
        "mesh": dict(mesh.shape),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
