"""Kernel-tier tests (ISSUE 16 "Raw-speed kernel tier").

Four contracts, one file:

- flash attention parity fwd+bwd against the reference oracle across
  causal/non-causal and sequence lengths that straddle the 8-aligned
  fallback boundary, plus the fallback itself: visible (once-per-process
  warning + ``kftpu_kernel_fallback_total``), never silent, never wrong.
- the fused shard-local Adam update (ops/fused_adam.py) ≤1e-5 vs the
  stock optax chain it replaces, including through ``make_optimizer``.
- AOT cache-key honesty: the kernel tier rotates ``recipe_fingerprint``
  AND ``aot.step_key``, and an executable exported under one tier's key
  can never be loaded under another's (PR 9 warning-fallback path).
- the int8 serving tier: quantize/dequantize round-trip, the parity
  gate refusing a past-threshold model with the delta LEDGERED, and the
  ``spec.kernels`` plumbing that selects all of the above.

Runs on the CPU conftest mesh; Pallas kernels run interpret=True — the
parity numbers are the same computation graph the TPU tiles execute.
"""

import inspect
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import importlib

# the ops package re-exports the flash_attention FUNCTION under the
# submodule's name, so attribute-style imports grab the function — go
# through importlib to monkeypatch module globals (_interpret)
fa = importlib.import_module("kubeflow_tpu.ops.flash_attention")
from kubeflow_tpu.ops.flash_attention import (flash_attention,  # noqa: E402
                                              reference_attention)
from kubeflow_tpu.ops.fused_adam import (FusedAdamState, fused_adam,
                                         reference_adam)

pytestmark = [pytest.mark.kernels, pytest.mark.compute]


def _qkv(b=2, s=64, h=2, d=16, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


def _counter_value(name, **labels):
    from kubeflow_tpu.obs import registry as obsreg
    return obsreg.default_registry().counter(
        name, "", labels=tuple(sorted(labels))).labels(**labels).value


# ---------------------------------------------------------------------------
# flash attention: parity across the fallback boundary
# ---------------------------------------------------------------------------


class TestFlashParity:
    # 64 = clean 8-aligned kernel path; 96 = uneven-block kernel path;
    # 65 and 7 straddle the TPU fallback boundary (no 8-aligned divisor)
    # but still run the interpret kernel on CPU — the same shapes the
    # fallback test below pins to the reference path.
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("s", [64, 96, 65, 7])
    def test_forward_matches_reference(self, causal, s):
        q, k, v = _qkv(s=s)
        out = flash_attention(q, k, v, causal=causal)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("s", [64, 65])
    def test_grad_matches_reference(self, causal, s):
        q, k, v = _qkv(s=s)

        def loss(attn, q, k, v):
            o = attn(q, k, v, causal=causal)
            return jnp.sum(o * jnp.cos(o))

        g_flash = jax.grad(lambda *a: loss(flash_attention, *a),
                           argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda *a: loss(
                lambda q, k, v, causal: reference_attention(
                    q, k, v, causal=causal), *a),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3,
                                       err_msg=f"d{name} s={s}")

    def test_grad_of_grad_on_fallback_path(self, monkeypatch):
        """Higher-order autodiff smoke: the Pallas kernel path is
        first-order only (its custom-VJP backward is itself a Pallas
        call with no VJP), so grad-of-grad rides the documented
        fallback — pin the TPU block picker (no 8-aligned divisor at
        s=7 → reference path) and differentiate twice."""
        monkeypatch.setattr(fa, "_interpret", lambda: False)
        q, _, _ = _qkv(b=1, s=7, h=1, d=8)

        def loss(q):
            return jnp.sum(flash_attention(q, q, q, causal=True) ** 2)

        def gnorm(q):
            return jnp.sum(jax.grad(loss)(q) ** 2)

        gg = jax.grad(gnorm)(q)
        ref = jax.grad(lambda q: jnp.sum(jax.grad(
            lambda q: jnp.sum(reference_attention(q, q, q) ** 2)
        )(q) ** 2))(q)
        np.testing.assert_allclose(gg, ref, atol=1e-4, rtol=1e-3)


class TestFlashFallback:
    """The unaligned-shape fallback: correct AND visible (ISSUE 16 —
    a job that requested flash but ran einsum was invisible before)."""

    def test_pick_block_boundary(self, monkeypatch):
        monkeypatch.setattr(fa, "_interpret", lambda: False)
        assert fa._pick_block(64) == 64        # 8-aligned divisor
        assert fa._pick_block(96) == 96        # <=128 and 8-aligned
        assert fa._pick_block(65) is None      # divisors 1/5/13/65
        assert fa._pick_block(7) is None
        monkeypatch.setattr(fa, "_interpret", lambda: True)
        assert fa._pick_block(65) == 65        # interpret: any divisor

    def test_fallback_counts_and_matches_reference(self, monkeypatch,
                                                   caplog):
        monkeypatch.setattr(fa, "_interpret", lambda: False)
        q, k, v = _qkv(s=65)
        before = _counter_value("kftpu_kernel_fallback_total",
                                kernel="flash_attention",
                                reason="unaligned-seq")
        with caplog.at_level("WARNING", logger=fa.log.name):
            out = flash_attention(q, k, v, causal=True)
            out2 = flash_attention(q, k, v, causal=True)
        after = _counter_value("kftpu_kernel_fallback_total",
                               kernel="flash_attention",
                               reason="unaligned-seq")
        # the counter ticks per fallen-back trace; the WARNING fires at
        # most once per process (the guard set persists across tests,
        # so assert membership, not caplog count)
        assert after == before + 2
        assert ("flash_attention", "unaligned-seq") in fa._warned_fallbacks
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(out2, ref, atol=2e-5, rtol=2e-5)

    def test_with_lse_refuses_unaligned(self, monkeypatch):
        # ring attention's chunk-merge NEEDS the kernel lse — a silent
        # fallback would hand it garbage, so this path raises instead
        monkeypatch.setattr(fa, "_interpret", lambda: False)
        q, k, v = _qkv(s=65)
        with pytest.raises(ValueError, match="with_lse"):
            flash_attention(q, k, v, causal=False, with_lse=True)


# ---------------------------------------------------------------------------
# fused Adam: the optimizer rung
# ---------------------------------------------------------------------------


def _toy_params():
    """Mixed tree: 2-D decayed leaves (odd shapes exercise the pad/
    unpad), 1-D undecayed leaves — the decay_mask split make_optimizer
    uses (ndim > 1)."""
    k = jax.random.PRNGKey(3)
    ks = jax.random.split(k, 4)
    return {
        "dense": {"kernel": jax.random.normal(ks[0], (7, 5)),
                  "bias": jax.random.normal(ks[1], (5,))},
        "head": {"kernel": jax.random.normal(ks[2], (5, 13)),
                 "bias": jax.random.normal(ks[3], (13,))},
    }


def _decay_mask(params):
    return jax.tree.map(lambda p: p.ndim > 1, params)


class TestFusedAdam:
    def test_multi_step_parity(self):
        sched = optax.cosine_decay_schedule(1e-2, decay_steps=10)
        kw = dict(b1=0.9, b2=0.999, eps=1e-8, weight_decay=1e-4,
                  mask=_decay_mask)
        fused = fused_adam(sched, **kw)
        ref = reference_adam(sched, **kw)
        params_f = params_r = _toy_params()
        state_f = fused.init(params_f)
        state_r = ref.init(params_r)
        assert isinstance(state_f, FusedAdamState)
        for step in range(5):
            g = jax.tree.map(
                lambda p: jnp.sin(p + step), params_f)
            up_f, state_f = fused.update(g, state_f, params_f)
            params_f = optax.apply_updates(params_f, up_f)
            up_r, state_r = ref.update(
                jax.tree.map(lambda p: jnp.sin(p + step), params_r),
                state_r, params_r)
            params_r = optax.apply_updates(params_r, up_r)
        deltas = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            params_f, params_r)
        assert max(jax.tree.leaves(deltas)) <= 1e-5, deltas

    def test_parity_under_jit(self):
        fused = fused_adam(1e-3, weight_decay=1e-4, mask=_decay_mask)
        ref = reference_adam(1e-3, weight_decay=1e-4, mask=_decay_mask)
        params = _toy_params()
        g = jax.tree.map(jnp.cos, params)

        def one(opt):
            @jax.jit
            def step(state, params):
                up, state = opt.update(g, state, params)
                return optax.apply_updates(params, up), state
            return step

        pf, _ = one(fused)(fused.init(params), params)
        pr, _ = one(ref)(ref.init(params), params)
        deltas = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))), pf, pr)
        assert max(jax.tree.leaves(deltas)) <= 1e-5

    def test_requires_params(self):
        fused = fused_adam(1e-3)
        params = _toy_params()
        state = fused.init(params)
        with pytest.raises(ValueError):
            fused.update(jax.tree.map(jnp.cos, params), state, None)

    def test_make_optimizer_fused_tier_parity(self):
        from kubeflow_tpu.runtime.recipe import make_optimizer
        common = dict(name="adam", learning_rate=1e-3,
                      schedule="cosine", total_steps=10,
                      weight_decay=1e-4, grad_clip=1.0)
        opt_f, _ = make_optimizer(kernels="fused_adam", **common)
        opt_s, _ = make_optimizer(kernels="stock", **common)
        params_f = params_s = _toy_params()
        state_f, state_s = opt_f.init(params_f), opt_s.init(params_s)
        for step in range(3):
            g = jax.tree.map(lambda p: jnp.sin(p) * 3.0, params_f)
            up, state_f = opt_f.update(g, state_f, params_f)
            params_f = optax.apply_updates(params_f, up)
            g = jax.tree.map(lambda p: jnp.sin(p) * 3.0, params_s)
            up, state_s = opt_s.update(g, state_s, params_s)
            params_s = optax.apply_updates(params_s, up)
        deltas = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            params_f, params_s)
        assert max(jax.tree.leaves(deltas)) <= 1e-5, deltas

    def test_make_optimizer_fused_tier_requires_adam(self):
        from kubeflow_tpu.runtime.recipe import make_optimizer
        with pytest.raises(ValueError, match="requires optimizer"):
            make_optimizer(name="momentum", kernels="fused_adam")

    def test_make_optimizer_rejects_unknown_tier(self):
        from kubeflow_tpu.runtime.recipe import make_optimizer
        with pytest.raises(ValueError, match="kernels"):
            make_optimizer(name="adam", kernels="bogus")


# ---------------------------------------------------------------------------
# cache-key honesty: the tier must rotate every executable key
# ---------------------------------------------------------------------------


class TestCacheKeyHonesty:
    def test_recipe_fingerprint_rotates_with_tier(self):
        from kubeflow_tpu.runtime.recipe import recipe_fingerprint
        base = dict(workload="transformer", optimizer="adam", lr=1e-3)
        stock = recipe_fingerprint(
            kernels={"attention": "einsum", "optimizer": "stock"}, **base)
        flash = recipe_fingerprint(
            kernels={"attention": "flash", "optimizer": "stock"}, **base)
        fused = recipe_fingerprint(
            kernels={"attention": "einsum", "optimizer": "fused_adam"},
            **base)
        assert len({stock, flash, fused}) == 3

    def test_step_key_rotates_with_tier(self):
        from kubeflow_tpu.runtime import aot
        base = dict(topology="v5e-8", num_slices=1,
                    model_fingerprint="m1", weight_update="replicated",
                    sharding={"data": 8}, global_batch=64)
        k_stock = aot.step_key(
            kernels={"attention": "einsum", "optimizer": "stock"}, **base)
        k_flash = aot.step_key(
            kernels={"attention": "flash", "optimizer": "stock"}, **base)
        k_fused = aot.step_key(
            kernels={"attention": "einsum", "optimizer": "fused_adam"},
            **base)
        assert len({k_stock, k_flash, k_fused}) == 3
        # deterministic per tier
        assert k_flash == aot.step_key(
            kernels={"attention": "flash", "optimizer": "stock"}, **base)

    def test_wrong_tier_executable_falls_back(self, tmp_path):
        """Two recipes differing ONLY in kernel tier get distinct keys
        and distinct cache files; a stock-tier executable hand-copied
        to the flash tier's path is refused by the embedded key (the
        PR 9 load_step warning path) — never executed."""
        from kubeflow_tpu.runtime import aot

        @jax.jit
        def fn(x):
            return x * 2.0

        x = jnp.arange(8.0)
        comp = fn.lower(x).compile()
        sig = aot.abstract_signature(x)
        base = dict(topology="cpu-1", num_slices=1,
                    model_fingerprint="m1", weight_update="replicated",
                    sharding={"data": 1}, global_batch=8)
        k_stock = aot.step_key(kernels={"optimizer": "stock"}, **base)
        k_fused = aot.step_key(kernels={"optimizer": "fused_adam"},
                               **base)
        assert k_stock != k_fused
        path = aot.export_step(str(tmp_path), k_stock, comp, sig)
        assert path and os.path.exists(path)
        # distinct cache entries: the fused key's slot is a clean miss
        assert aot.load_step(str(tmp_path), k_fused, sig) is None
        # a hand-copied wrong-tier file is detected by the embedded key
        os.rename(aot._path(str(tmp_path), k_stock),
                  aot._path(str(tmp_path), k_fused))
        before = _counter_value("kftpu_aot_executable_total",
                                outcome="key-mismatch")
        assert aot.load_step(str(tmp_path), k_fused, sig) is None
        assert _counter_value("kftpu_aot_executable_total",
                              outcome="key-mismatch") == before + 1
        # the record on disk still carries the honest (stock) key
        with open(aot._path(str(tmp_path), k_fused), "rb") as f:
            assert pickle.load(f)["key"] == k_stock


# ---------------------------------------------------------------------------
# spec.kernels plumbing: api → controller env → worker CLI → manifest
# ---------------------------------------------------------------------------


class TestKernelSpecPlumbing:
    def test_round_trip_and_env(self):
        from kubeflow_tpu.api.trainingjob import KernelSpec
        spec = KernelSpec.from_dict(
            {"attention": "flash", "optimizer": "fused_adam"})
        assert spec.attention == "flash"
        assert spec.serving is None
        assert spec.to_dict() == {"attention": "flash",
                                  "optimizer": "fused_adam"}
        assert spec.to_env() == {"KFTPU_KERNEL_ATTENTION": "flash",
                                 "KFTPU_KERNEL_OPTIMIZER": "fused_adam"}
        # unset tier renders nothing: the worker default stays opt-in
        assert KernelSpec.from_dict(None).to_env() == {}

    def test_rejects_bad_values(self):
        from kubeflow_tpu.api.trainingjob import KernelSpec
        with pytest.raises(ValueError, match="kernels.attention"):
            KernelSpec.from_dict({"attention": "paged"})
        with pytest.raises(ValueError, match="unknown kernel-tier"):
            KernelSpec.from_dict({"atention": "flash"})
        with pytest.raises(ValueError, match="must be a mapping"):
            KernelSpec.from_dict("flash")

    def test_manifest_round_trip(self):
        from kubeflow_tpu.api.trainingjob import TrainingJob
        job = TrainingJob.from_manifest({
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "kern", "namespace": "ns"},
            "spec": {
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [{"name": "c"}]}},
                }},
                "kernels": {"attention": "flash",
                            "optimizer": "fused_adam",
                            "serving": "int8"}},
        })
        job.validate()
        assert job.kernels.attention == "flash"
        out = job.to_manifest()
        assert out["spec"]["kernels"] == {
            "attention": "flash", "optimizer": "fused_adam",
            "serving": "int8"}

    def test_controller_renders_env(self):
        """The operator's pod env must carry every set knob — the lint
        mirror of controllers/tpujob.py's kernels.to_env call."""
        from kubeflow_tpu.controllers import tpujob as ctrl
        src = inspect.getsource(ctrl)
        assert "kernels.to_env()" in src

    def test_worker_consumes_cli_and_env(self):
        from kubeflow_tpu.runtime import worker
        sig = inspect.signature(worker.train)
        for p in ("kernel_attention", "kernel_optimizer",
                  "kernel_serving"):
            assert p in sig.parameters, p
        src = inspect.getsource(worker)
        for flag in ("--kernel-attention", "--kernel-optimizer",
                     "--kernel-serving"):
            assert flag in src, flag
        for env in ("KFTPU_KERNEL_ATTENTION", "KFTPU_KERNEL_OPTIMIZER",
                    "KFTPU_KERNEL_SERVING"):
            assert env in src, env

    def test_manifest_schema_names_the_tiers(self):
        from kubeflow_tpu.manifests.training import _job_schema
        schema = _job_schema("replicaSpecs", ["Coordinator"])
        spec_props = schema["properties"]["spec"]["properties"]
        kern = spec_props["kernels"]["properties"]
        assert kern["attention"]["enum"] == ["einsum", "flash", "ring"]
        assert kern["optimizer"]["enum"] == ["stock", "fused_adam"]
        assert kern["serving"]["enum"] == ["stock", "int8"]


# ---------------------------------------------------------------------------
# int8 serving tier: quantize, measure, gate
# ---------------------------------------------------------------------------


def _gate_toy():
    """The within-channel-outlier servable: per-channel absmax scaling
    is robust to CROSS-channel range, so the refusal case needs an
    outlier INSIDE a decisive channel — W[7,1]=100 stretches column 1's
    int8 resolution to ~0.79, swallowing the 0.3-margin decisions the
    eye(8) calibration rows depend on. Measured delta: 0.125."""
    from kubeflow_tpu.serving.servable import Servable
    W = np.zeros((8, 3), np.float32)
    W[7, 1] = 100.0
    W[0, 1] = 0.3
    W[0, 2] = 0.2
    W[7, 2] = 0.1
    params = {"w": jnp.asarray(W)}

    def predict(params, x):
        logits = x @ params["w"]
        return {"logits": logits, "classes": jnp.argmax(logits, axis=-1)}

    servable = Servable(
        name="gate-toy", predict_fn=predict, params=params,
        input_signature={"inputs": {"shape": [-1, 8],
                                    "dtype": "float32"}})
    calib = [np.eye(8, dtype=np.float32)]
    return servable, calib


class TestInt8Serving:
    def test_quantize_dequantize_round_trip(self):
        from kubeflow_tpu.serving.servable import (dequantize_params,
                                                   quantize_params_int8)
        k = jax.random.PRNGKey(0)
        params = {"w": jax.random.normal(k, (16, 8)),
                  "b": jnp.ones((8,))}
        qtree, stats = quantize_params_int8(params)
        assert stats["quantized_leaves"] == 1      # 1-D bias stays f32
        assert stats["float_leaves"] == 1
        assert stats["weight_bytes_int8"] < stats["weight_bytes_float"]
        deq = dequantize_params(qtree)
        # per-channel absmax error bound: scale/2 = absmax/254
        scale = np.abs(np.asarray(params["w"])).max(axis=0) / 127.0
        err = np.abs(np.asarray(deq["w"]) - np.asarray(params["w"]))
        assert (err <= scale[None, :] * 0.5 + 1e-7).all()
        np.testing.assert_array_equal(deq["b"], params["b"])

    def test_benign_model_passes_gate(self):
        from kubeflow_tpu.serving.servable import quantize_servable
        servable, _ = _gate_toy()
        # gaussian calibration rows rarely cross the outlier channel's
        # resolution cliff — but the eye-rows case below always does;
        # here use a benign weight matrix instead
        servable.params = {"w": jax.random.normal(
            jax.random.PRNGKey(1), (8, 3))}
        q = quantize_servable(servable, max_delta=0.05)
        assert q.quant["kernel"] == "int8"
        assert q.quant["accuracy_delta"] <= 0.05
        assert "quantization" in q.metadata()
        x = np.random.default_rng(0).standard_normal(
            (4, 8)).astype(np.float32)
        out_f = servable.predict(x)
        out_q = q.predict(x)
        assert out_q["logits"].shape == out_f["logits"].shape

    def test_gate_refuses_and_ledgers_the_delta(self):
        from kubeflow_tpu.serving.servable import (QuantizationRefused,
                                                   quantize_servable)
        servable, calib = _gate_toy()
        with pytest.raises(QuantizationRefused, match="0.125"):
            quantize_servable(servable, calibration=calib,
                              max_delta=0.01)
        # same model under a permissive gate: the delta is LEDGERED,
        # never hidden — the dashboard reads it from metadata
        servable2, calib = _gate_toy()
        q = quantize_servable(servable2, calibration=calib,
                              max_delta=1.0)
        assert q.quant["accuracy_delta"] == pytest.approx(0.125)
        assert q.metadata()["quantization"]["accuracy_delta"] == \
            pytest.approx(0.125)

    def test_env_threshold_drives_the_gate(self, monkeypatch):
        from kubeflow_tpu.serving.servable import (INT8_MAX_DELTA_ENV,
                                                   QuantizationRefused,
                                                   quantize_servable)
        servable, calib = _gate_toy()
        monkeypatch.setenv(INT8_MAX_DELTA_ENV, "0.01")
        with pytest.raises(QuantizationRefused):
            quantize_servable(servable, calibration=calib)

    def test_repository_load_int8(self):
        from kubeflow_tpu.serving.servable import ModelRepository
        repo = ModelRepository()
        # explicit gate: the random-weights smoke model's near-tied
        # logits measure a few percent argmax delta (init RNG bits vary
        # with the process-global threefry flag, so don't pin tighter)
        servable = repo.load(
            "lm", "transformer_lm", kernels="int8", quant_max_delta=0.05,
            vocab_size=256, embed_dim=32, num_heads=2, head_dim=16,
            num_layers=1, mlp_dim=64, max_seq_len=16,
            dtype=jnp.float32)
        assert servable.quant is not None
        assert servable.quant["accuracy_delta"] <= 0.05
        tokens = np.random.default_rng(0).integers(
            0, 256, (2, 16)).astype(np.int32)
        out = servable.predict(tokens)
        assert out["next_token"].shape == (2,)

    def test_repository_rejects_unknown_tier(self):
        from kubeflow_tpu.serving.servable import ModelRepository
        with pytest.raises(ValueError, match="kernels"):
            ModelRepository().load("lm", "transformer_lm",
                                   kernels="int4", vocab_size=16,
                                   embed_dim=8, num_heads=1, head_dim=8,
                                   num_layers=1, mlp_dim=16,
                                   max_seq_len=8, dtype=jnp.float32)

    def test_batcher_notes_quant_delta(self):
        """The ledgered delta rides every sampled serving span — the
        dashboard's serving table shows it next to the SLO badge."""
        from kubeflow_tpu.serving.batcher import MicroBatcher
        from kubeflow_tpu.serving.servable import quantize_servable
        servable, calib = _gate_toy()
        q = quantize_servable(servable, calibration=calib,
                              max_delta=1.0)

        class _Ctx:
            def __init__(self):
                self.attrs = {}
                self.t_pipeline_end = None

            def note(self, **attrs):
                self.attrs.update(attrs)

            def stage(self, *a, **k):
                pass

            def device(self, *a, **k):
                pass

        batcher = MicroBatcher(q, max_latency_ms=1.0)
        try:
            ctx = _Ctx()
            x = np.eye(8, dtype=np.float32)[:2]
            batcher.predict(x, ctx=ctx)
            assert ctx.attrs["quant_delta"] == pytest.approx(0.125)
        finally:
            batcher.shutdown()
