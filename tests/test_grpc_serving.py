"""gRPC predict surface: the TF-Serving PredictionService the serving
manifests advertise on :9000 (tf-serving.libsonnet:137; the reference's
http-proxy client at components/k8s-model-server/http-proxy/server.py:27-40
speaks exactly this wire contract)."""

import numpy as np
import pytest

from kubeflow_tpu.serving.grpc_server import HAVE_GRPC

pytestmark = pytest.mark.compute  # JAX compile tests: not in smoke tier

if not HAVE_GRPC:  # skip before touching the pb2 module (needs protobuf)
    pytest.skip("grpcio/protobuf unavailable", allow_module_level=True)

from kubeflow_tpu.serving import tpu_serving_pb2 as pb  # noqa: E402
from kubeflow_tpu.serving.grpc_server import (GrpcPredictServer,  # noqa: E402
                                              ndarray_to_tensor,
                                              predict_stub,
                                              tensor_to_ndarray)
from kubeflow_tpu.serving.http_server import ModelServer  # noqa: E402
from kubeflow_tpu.serving.servable import (ModelRepository,  # noqa: E402
                                           Servable)


class TestTensorCodec:
    def test_roundtrip_content(self):
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        b = tensor_to_ndarray(ndarray_to_tensor(a))
        np.testing.assert_array_equal(a, b)
        assert b.dtype == np.float32

    def test_roundtrip_dtypes(self):
        for dtype in (np.float64, np.int32, np.int64, np.uint8, np.bool_):
            a = np.array([[1, 0], [1, 1]], dtype=dtype)
            b = tensor_to_ndarray(ndarray_to_tensor(a))
            np.testing.assert_array_equal(a, b)
            assert b.dtype == dtype

    def test_val_fields_accepted(self):
        """Clients that fill float_val instead of tensor_content parse."""
        t = pb.TensorProto()
        t.dtype = pb.DT_FLOAT
        t.tensor_shape.dim.add().size = 2
        t.tensor_shape.dim.add().size = 2
        t.float_val.extend([1, 2, 3, 4])
        np.testing.assert_array_equal(
            tensor_to_ndarray(t), [[1, 2], [3, 4]])

    def test_scalar_broadcast(self):
        t = pb.TensorProto()
        t.dtype = pb.DT_INT32
        t.tensor_shape.dim.add().size = 3
        t.int_val.append(7)
        np.testing.assert_array_equal(tensor_to_ndarray(t), [7, 7, 7])

    def test_half_val_bit_pattern(self):
        """half_val carries raw float16 bits in int32 slots (TF idiom)."""
        a = np.array([1.5, -2.0], dtype=np.float16)
        t = pb.TensorProto()
        t.dtype = pb.DT_HALF
        t.tensor_shape.dim.add().size = 2
        t.half_val.extend(int(b) for b in a.view(np.uint16))
        np.testing.assert_array_equal(tensor_to_ndarray(t), a)


@pytest.fixture
def served():
    import grpc
    repo = ModelRepository()
    repo.add(Servable(name="double", predict_fn=lambda p, x: x * 2.0,
                      params=()))
    ms = ModelServer(repo, port=0)
    ms.start()
    gs = GrpcPredictServer(ms, host="127.0.0.1", port=0)
    gport = gs.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{gport}")
    stub = predict_stub(channel)
    yield ms, stub
    channel.close()
    gs.stop()
    ms.stop()


class TestPredictionService:
    def test_predict(self, served):
        _, stub = served
        req = pb.PredictRequest()
        req.model_spec.name = "double"
        req.inputs["instances"].CopyFrom(
            ndarray_to_tensor(np.array([[1.5, 2.5]], np.float32)))
        resp = stub["Predict"](req)
        out = tensor_to_ndarray(resp.outputs["outputs"])
        np.testing.assert_allclose(out, [[3.0, 5.0]])
        assert resp.model_spec.signature_name == "serving_default"

    def test_client_predict_grpc_helper(self, served):
        """serving.client.predict_grpc (the inception-client gRPC wire):
        REST-shaped result from the binary surface."""
        ms, _ = served
        from kubeflow_tpu.serving.client import _first_output, predict_grpc
        gs2 = GrpcPredictServer(ms, host="127.0.0.1", port=0)
        gport = gs2.start()
        try:
            res = predict_grpc(f"127.0.0.1:{gport}", "double",
                               [[2.0, 4.0]])
        finally:
            gs2.stop()
        preds = _first_output(res["predictions"])
        np.testing.assert_allclose(preds, [[4.0, 8.0]])

    def test_predict_shares_rest_batchers(self, served):
        """gRPC traffic goes through the same MicroBatcher as REST —
        one device queue per model."""
        ms, stub = served
        req = pb.PredictRequest()
        req.model_spec.name = "double"
        req.inputs["instances"].CopyFrom(
            ndarray_to_tensor(np.zeros((1, 2), np.float32)))
        stub["Predict"](req)
        assert "double" in ms._batchers

    def test_unknown_model_not_found(self, served):
        import grpc
        _, stub = served
        req = pb.PredictRequest()
        req.model_spec.name = "ghost"
        req.inputs["instances"].CopyFrom(
            ndarray_to_tensor(np.zeros((1, 2), np.float32)))
        with pytest.raises(grpc.RpcError) as exc:
            stub["Predict"](req)
        assert exc.value.code() == grpc.StatusCode.NOT_FOUND

    def test_empty_inputs_invalid(self, served):
        import grpc
        _, stub = served
        req = pb.PredictRequest()
        req.model_spec.name = "double"
        with pytest.raises(grpc.RpcError) as exc:
            stub["Predict"](req)
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    def test_get_model_status(self, served):
        _, stub = served
        req = pb.GetModelStatusRequest()
        req.model_spec.name = "double"
        resp = stub["GetModelStatus"](req)
        assert resp.model_version_status[0].state == \
            pb.ModelVersionStatus.AVAILABLE
