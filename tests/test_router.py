"""Experiment-routing tests: A/B split, epsilon-greedy bandit, shadow
traffic (the seldon abtest/mab/shadow prototypes, SURVEY.md §2.3)."""

import json
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.compute  # Servable predicts jit-compile

from kubeflow_tpu.serving.router import (ABTestRouter, EpsilonGreedyRouter,
                                         RoutedModel, Router, ShadowRouter)


class TestABTest:
    def test_split_follows_weights(self):
        r = ABTestRouter(["a", "b"], weights=[0.8, 0.2], seed=1)
        picks = [r.route() for _ in range(5000)]
        frac_a = picks.count("a") / len(picks)
        assert 0.75 < frac_a < 0.85

    def test_bad_weights_rejected(self):
        with pytest.raises(ValueError):
            ABTestRouter(["a", "b"], weights=[1.0])
        with pytest.raises(ValueError):
            ABTestRouter(["a", "b"], weights=[-1, 2])
        with pytest.raises(ValueError):
            ABTestRouter([])


class TestEpsilonGreedy:
    def test_explores_then_exploits_best_arm(self):
        r = EpsilonGreedyRouter(["bad", "good"], epsilon=0.1, seed=3)
        # reward model: good=0.9, bad=0.1
        for _ in range(300):
            arm = r.route()
            r.record(arm, reward=0.9 if arm == "good" else 0.1)
        stats = {s["name"]: s for s in r.stats_dict()}
        assert stats["good"]["requests"] > stats["bad"]["requests"] * 3
        assert stats["good"]["meanReward"] == pytest.approx(0.9)

    def test_unexplored_arms_tried_first(self):
        r = EpsilonGreedyRouter(["a", "b", "c"], epsilon=0.0, seed=0)
        first3 = set()
        for _ in range(3):
            arm = r.route()
            first3.add(arm)
            r.record(arm, reward=1.0)
        assert first3 == {"a", "b", "c"}


class FakeRepo:
    def __init__(self, outputs, fail=()):
        self.outputs = outputs
        self.fail = set(fail)
        self.calls = []

    def get(self, name):
        repo = self

        class S:
            def predict(self, x):
                repo.calls.append(name)
                if name in repo.fail:
                    raise RuntimeError(f"{name} down")
                return np.full(len(x), repo.outputs[name])

        return S()


class TestRoutedModel:
    def test_shadow_gets_copy_result_from_primary(self):
        repo = FakeRepo({"prod": 1.0, "canary": 2.0})
        routed = RoutedModel(ShadowRouter("prod", "canary"), repo)
        out = routed.predict(np.zeros(4))
        assert (out == 1.0).all()  # primary's answer
        assert repo.calls == ["prod", "canary"]  # shadow got the copy

    def test_shadow_failure_never_breaks_serving(self):
        repo = FakeRepo({"prod": 1.0, "canary": 2.0}, fail={"canary"})
        routed = RoutedModel(ShadowRouter("prod", "canary"), repo)
        out = routed.predict(np.zeros(2))
        assert (out == 1.0).all()
        stats = {s["name"]: s for s in routed.router.stats_dict()}
        assert stats["canary"]["failures"] == 1

    def test_primary_failure_recorded_and_raised(self):
        repo = FakeRepo({"a": 1.0}, fail={"a"})
        routed = RoutedModel(Router(["a"]), repo)
        routed.router.route = lambda: "a"
        with pytest.raises(RuntimeError):
            routed.predict(np.zeros(2))
        assert routed.router.stats_dict()[0]["failures"] == 1


class TestRouterHTTP:
    def test_router_predict_and_feedback_over_http(self):
        from kubeflow_tpu.serving.http_server import ModelServer
        from kubeflow_tpu.serving.servable import ModelRepository, Servable
        import jax.numpy as jnp

        repo = ModelRepository()
        for name, scale in (("m1", 2.0), ("m2", 3.0)):
            repo.add(Servable(
                name=name, predict_fn=lambda p, x, s=scale: x * s,
                params={}, input_signature=((None, 2), jnp.float32)))
        server = ModelServer(repository=repo, host="127.0.0.1", port=0)
        routed = RoutedModel(ABTestRouter(["m1", "m2"], seed=5), repo,
                             name="exp1")
        server.add_router(routed)
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            req = urllib.request.Request(
                f"{base}/v1/routers/exp1:predict",
                data=json.dumps({"instances": [[1.0, 1.0]]}).encode())
            with urllib.request.urlopen(req) as r:
                preds = json.loads(r.read())["predictions"]
            assert preds[0][0] in (2.0, 3.0)

            req = urllib.request.Request(
                f"{base}/v1/routers/exp1:feedback",
                data=json.dumps({"arm": "m1", "reward": 0.7}).encode())
            with urllib.request.urlopen(req) as r:
                status = json.loads(r.read())
            arms = {a["name"]: a for a in status["arms"]}
            # feedback adds a reward observation but NOT a request — a
            # :feedback call must never double-count traffic
            assert arms["m1"]["rewardCount"] >= 1

            with urllib.request.urlopen(f"{base}/v1/routers/exp1") as r:
                status = json.loads(r.read())
            assert status["routerType"] == "ABTestRouter"
        finally:
            server.stop()
