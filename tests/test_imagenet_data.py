"""Real-data input path: record shards → ImageNetSource → the worker loop
(the launcher.py --data_dir analog), plus the BASELINE config-matrix
benchmark driver. Runs on the virtual CPU mesh."""

from __future__ import annotations

import csv
import os

import numpy as np
import pytest

from kubeflow_tpu.data.imagenet import (ImageNetSource, read_meta,
                                        record_bytes, write_shards)
from kubeflow_tpu.data.pipeline import epoch_order

pytestmark = pytest.mark.compute  # JAX trace/compile tests: excluded from smoke tier

SIZE = 16          # tiny images so resnet runs fast on CPU
N = 48
CLASSES = 10


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    rng = np.random.default_rng(7)
    images = rng.integers(0, 256, (N, SIZE, SIZE, 3), dtype=np.uint8)
    labels = np.arange(N) % CLASSES
    d = tmp_path_factory.mktemp("imagenet")
    meta = write_shards(str(d), images, labels, shard_records=20,
                        num_classes=CLASSES)
    assert meta["num_records"] == N
    return str(d), images, labels


class TestShardFormat:
    def test_meta_roundtrip(self, data_dir):
        d, *_ = data_dir
        meta = read_meta(d)
        assert meta["image_size"] == SIZE
        assert meta["num_classes"] == CLASSES
        assert meta["record_bytes"] == record_bytes(SIZE)
        # 48 records / 20 per shard = 3 shards
        assert len([f for f in os.listdir(d) if f.endswith(".rec")]) == 3

    def test_batches_are_seed_deterministic(self, data_dir):
        d, images, labels = data_dir
        with ImageNetSource(d, batch_size=8, augment=False) as src:
            first = [b["labels"].copy() for b in src.epoch(0, seed=3)]
        with ImageNetSource(d, batch_size=8, augment=False) as src:
            second = [b["labels"].copy() for b in src.epoch(0, seed=3)]
        assert len(first) == 6
        for a, b in zip(first, second):
            np.testing.assert_array_equal(a, b)
        # and the order is the pinned epoch permutation of the record files
        order = epoch_order(N, 3)
        np.testing.assert_array_equal(
            np.concatenate(first), labels[order][: 6 * 8])

    def test_epochs_reshuffle(self, data_dir):
        d, *_ = data_dir
        with ImageNetSource(d, batch_size=8, augment=False) as src:
            e0 = np.concatenate([b["labels"] for b in src.epoch(0, seed=3)])
            e1 = np.concatenate([b["labels"] for b in src.epoch(1, seed=3)])
        assert not np.array_equal(e0, e1)
        assert sorted(e0) == sorted(e1)

    def test_images_decoded_and_normalized(self, data_dir):
        d, images, labels = data_dir
        with ImageNetSource(d, batch_size=8, augment=False) as src:
            batch = next(src.epoch(0, seed=1))
        order = epoch_order(N, 1)
        from kubeflow_tpu.data.imagenet import MEAN_RGB, STDDEV_RGB
        expect = (images[order[0]].astype(np.float32) / 255.0
                  - MEAN_RGB) / STDDEV_RGB
        # the fused path computes x*(1/(255*std)) - mean/std: equal up to
        # f32 rounding, so compare with an absolute tolerance too
        np.testing.assert_allclose(batch["images"][0], expect,
                                   rtol=1e-5, atol=1e-5)

    def test_augment_deterministic_per_seed(self, data_dir):
        d, *_ = data_dir
        with ImageNetSource(d, batch_size=8, augment=True) as src:
            a = next(src.epoch(0, seed=5))["images"].copy()
        with ImageNetSource(d, batch_size=8, augment=True) as src:
            b = next(src.epoch(0, seed=5))["images"].copy()
        with ImageNetSource(d, batch_size=8, augment=True) as src:
            c = next(src.epoch(0, seed=6))["images"].copy()
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_bad_dir_rejected(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            ImageNetSource(str(tmp_path / "nope"), batch_size=4)

    def test_too_few_records_rejected(self, tmp_path):
        rng = np.random.default_rng(0)
        d = str(tmp_path / "small")
        write_shards(d, rng.integers(0, 256, (4, SIZE, SIZE, 3),
                                     dtype=np.uint8),
                     np.zeros(4, np.int64), num_classes=1)
        with pytest.raises(ValueError, match="records"):
            ImageNetSource(d, batch_size=8)

    def test_resume_skips_consumed_batches(self, data_dir):
        d, *_ = data_dir
        with ImageNetSource(d, batch_size=8, augment=True) as src:
            full = [b["labels"].copy() for _, b in
                    zip(range(8), src.batches(seed=3))]
            imgs = [b["images"].copy() for _, b in
                    zip(range(8), src.batches(seed=3))]
        with ImageNetSource(d, batch_size=8, augment=True) as src:
            resumed = list(zip(range(4), src.batches(seed=3, start_batch=4)))
        for i, (_, b) in enumerate(resumed):
            np.testing.assert_array_equal(b["labels"], full[4 + i])
            np.testing.assert_array_equal(b["images"], imgs[4 + i])


@pytest.mark.slow
class TestWorkerRealData:
    def test_train_consumes_records_deterministically(self, data_dir):
        d, *_ = data_dir
        from kubeflow_tpu.runtime.worker import train
        kw = dict(workload="resnet50", steps=3, global_batch=8,
                  data_dir=d, sync_every=1, seed=11)
        r1 = train(**kw)
        r2 = train(**kw)
        assert r1.steps == 3
        assert np.isfinite(r1.final_metrics["loss"])
        # the whole run is a function of (data, seed)
        assert r1.final_metrics["loss"] == pytest.approx(
            r2.final_metrics["loss"])

    def test_env_contract(self, data_dir, monkeypatch):
        d, *_ = data_dir
        from kubeflow_tpu.runtime.worker import train
        monkeypatch.setenv("KFTPU_DATA_DIR", d)
        r = train(workload="resnet50", steps=1, global_batch=8)
        assert r.steps == 1

    def test_non_image_workload_rejects_data_dir(self, data_dir):
        d, *_ = data_dir
        from kubeflow_tpu.runtime.worker import train
        with pytest.raises(ValueError, match="data-dir"):
            train(workload="transformer", steps=1, global_batch=8,
                  data_dir=d)


@pytest.mark.slow
class TestOperatorDataDir:
    def test_data_dir_rendered_as_env(self):
        from kubeflow_tpu.api.trainingjob import TrainingJob
        manifest = {
            "apiVersion": "kubeflow.org/v1", "kind": "TFJob",
            "metadata": {"name": "j", "namespace": "default"},
            "spec": {
                "dataDir": "/data/imagenet",
                "tfReplicaSpecs": {"Worker": {
                    "replicas": 1,
                    "template": {"spec": {"containers": [
                        {"name": "worker", "image": "x"}]}}}},
            },
        }
        job = TrainingJob.from_manifest(manifest)
        assert job.data_dir == "/data/imagenet"
        assert job.to_manifest()["spec"]["dataDir"] == "/data/imagenet"

    def test_eval_data_dir_rendered_as_env(self):
        from kubeflow_tpu.api.trainingjob import TrainingJob
        from kubeflow_tpu.cluster import FakeCluster
        from kubeflow_tpu.controllers.runtime import Manager
        from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
        cluster = FakeCluster(auto_schedule=False, auto_run=False)
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create({
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "j", "namespace": "default"},
            "spec": {
                "dataDir": "/data/train", "evalDataDir": "/data/val",
                "tensorboardDir": "/logs/tb",
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "worker", "image": "x"}]}}}},
            },
        })
        mgr.run_pending()
        pods = cluster.list("v1", "Pod", "default")
        assert pods
        env = {e["name"]: e["value"]
               for c in pods[0]["spec"]["containers"]
               for e in c.get("env", [])}
        assert env["KFTPU_DATA_DIR"] == "/data/train"
        assert env["KFTPU_EVAL_DATA_DIR"] == "/data/val"
        assert env["KFTPU_TB_DIR"] == "/logs/tb"

    def test_worker_eval_on_holdout_shards(self, data_dir):
        d, *_ = data_dir
        from kubeflow_tpu.runtime.worker import train
        r = train(workload="resnet50", steps=2, global_batch=8,
                  data_dir=d, eval_data_dir=d, eval_every=2,
                  eval_batches=2, sync_every=1, seed=5)
        assert "top1" in r.final_metrics and "top5" in r.final_metrics
        assert 0.0 <= r.final_metrics["top1"] <= 1.0

    def test_eval_holdout_smaller_than_batch_survives(self, data_dir,
                                                      tmp_path):
        """A train batch larger than the whole val set must clamp the eval
        batch, not kill the run at startup; eval_batches=0 runs the full
        holdout (one pass, every record counted once)."""
        d, images, labels = data_dir
        val = str(tmp_path / "val")
        write_shards(val, images[:8], labels[:8], num_classes=CLASSES)
        from kubeflow_tpu.runtime.worker import train
        r = train(workload="resnet50", steps=1, global_batch=16,
                  data_dir=d, eval_data_dir=val, eval_every=1,
                  eval_batches=0, sync_every=1, seed=5)
        assert "top1" in r.final_metrics


@pytest.mark.slow
class TestBenchmarkMatrix:
    def test_matrix_produces_csv_per_config(self, tmp_path):
        from kubeflow_tpu.workflows.kubebench import (CONFIG_MATRIX,
                                                      benchmark_matrix)
        out = str(tmp_path / "matrix")
        rows = benchmark_matrix(
            out, steps=2, global_batch=8,
            workload_kwargs={"image_size": 16, "num_classes": 10},
            configs=["tf_job_simple", "katib_study"])
        assert set(rows) == {"tf_job_simple", "katib_study"}
        for name in rows:
            path = os.path.join(out, f"{name}.csv")
            with open(path) as f:
                data = list(csv.DictReader(f))
            assert len(data) == 1
        assert rows["tf_job_simple"]["examples_per_sec"] > 0
        assert rows["katib_study"]["metric_best_learning_rate"] > 0
        # the full matrix covers every BASELINE.json config, plus the
        # opt-in fused-blocks variant row
        assert set(CONFIG_MATRIX) == {
            "tf_job_simple", "tf_job_dp_allreduce", "pytorch_ddp",
            "mpi_horovod", "tf_job_fused_blocks", "katib_study"}


class TestNativeAugment:
    """The C++ augment kernel and the numpy fallback are the same
    function: bit-identical outputs from the shared splitmix64 spec."""

    def test_native_matches_python(self):
        from kubeflow_tpu.data.imagenet import (MEAN_RGB, STDDEV_RGB,
                                                _py_augment)
        from kubeflow_tpu.data.native import (native_augment,
                                              native_available)
        if not native_available():
            pytest.skip("native toolchain unavailable")
        rng = np.random.default_rng(3)
        images = rng.integers(0, 256, (12, SIZE, SIZE, 3), dtype=np.uint8)
        for base in (0, 12345, 2 ** 63 + 17):
            want = _py_augment(images, base, 4, do_flip=True, do_crop=True)
            got = native_augment(images, base, 4, MEAN_RGB, STDDEV_RGB)
            np.testing.assert_array_equal(got, want)
        # no-augment (eval) path too
        want = _py_augment(images, 7, 4, do_flip=False, do_crop=False)
        got = native_augment(images, 7, 4, MEAN_RGB, STDDEV_RGB,
                             do_flip=False, do_crop=False)
        np.testing.assert_array_equal(got, want)

    def test_multithreaded_matches_single(self):
        from kubeflow_tpu.data.imagenet import MEAN_RGB, STDDEV_RGB
        from kubeflow_tpu.data.native import (native_augment,
                                              native_available)
        if not native_available():
            pytest.skip("native toolchain unavailable")
        rng = np.random.default_rng(4)
        images = rng.integers(0, 256, (33, SIZE, SIZE, 3), dtype=np.uint8)
        a = native_augment(images, 99, 4, MEAN_RGB, STDDEV_RGB,
                           num_threads=1)
        b = native_augment(images, 99, 4, MEAN_RGB, STDDEV_RGB,
                           num_threads=8)
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
class TestUint8DeviceNormalize:
    """uint8 input mode: augmented bytes ship to the device, normalize
    runs in jit — the composition equals the host-normalized path."""

    def test_uint8_plus_device_normalize_equals_host(self, data_dir):
        import jax
        from kubeflow_tpu.data.imagenet import device_normalize
        d, *_ = data_dir
        with ImageNetSource(d, batch_size=8, augment=True,
                            output="uint8") as src:
            b_u8 = next(src.epoch(0, seed=9))
        with ImageNetSource(d, batch_size=8, augment=True) as src:
            b_f32 = next(src.epoch(0, seed=9))
        assert b_u8["images"].dtype == np.uint8
        np.testing.assert_array_equal(b_u8["labels"], b_f32["labels"])
        on_device = jax.jit(device_normalize)(b_u8["images"])
        np.testing.assert_allclose(np.asarray(on_device), b_f32["images"],
                                   rtol=1e-6, atol=1e-6)

    def test_native_u8_matches_python(self):
        from kubeflow_tpu.data.imagenet import _py_augment
        from kubeflow_tpu.data.native import (native_augment_u8,
                                              native_available)
        if not native_available():
            pytest.skip("native toolchain unavailable")
        rng = np.random.default_rng(5)
        images = rng.integers(0, 256, (9, SIZE, SIZE, 3), dtype=np.uint8)
        want = _py_augment(images, 42, 4, do_flip=True, do_crop=True,
                           normalize=False)
        got = native_augment_u8(images, 42, 4)
        assert got.dtype == np.uint8
        np.testing.assert_array_equal(got, want)

    def test_worker_trains_on_uint8_path(self, data_dir):
        d, *_ = data_dir
        from kubeflow_tpu.runtime.worker import train
        r = train(workload="resnet50", steps=2, global_batch=8,
                  data_dir=d, sync_every=1, seed=2)
        assert r.steps == 2
        assert np.isfinite(r.final_metrics["loss"])

    def test_bad_output_mode_rejected(self, data_dir):
        d, *_ = data_dir
        with pytest.raises(ValueError, match="output"):
            ImageNetSource(d, batch_size=8, output="float64")


@pytest.mark.slow
class TestEvalTailHandling:
    """ADVICE r3: eval_batches=0 must count EVERY holdout record — the
    tail batch comes through short (drop_remainder=False), gets padded
    to the compiled shape, and the padding is weight-masked out."""

    def test_drop_remainder_false_yields_short_tail(self, data_dir):
        d, *_ = data_dir
        with ImageNetSource(d, batch_size=20, augment=False,
                            drop_remainder=False) as src:
            assert src.num_batches == 3  # 48 = 2*20 + tail of 8
            sizes = [b["labels"].shape[0] for b in src.epoch(0, seed=1)]
        assert sizes == [20, 20, 8]

    def test_eval_fn_weight_masks_padding_exactly(self):
        import jax

        from kubeflow_tpu.models.resnet import (init_fn, make_eval_fn,
                                                make_resnet)
        model = make_resnet(18, num_classes=CLASSES)
        params, variables = init_fn(model, image_size=SIZE, batch=2)(
            jax.random.PRNGKey(0))
        eval_fn = make_eval_fn(model)
        rng = np.random.default_rng(0)
        imgs = rng.normal(size=(6, SIZE, SIZE, 3)).astype(np.float32)
        labels = (np.arange(6) % CLASSES).astype(np.int32)
        full = eval_fn(params, variables,
                       {"images": imgs, "labels": labels})
        # pad 2 garbage rows and mask them: metrics must match exactly
        pimgs = np.concatenate(
            [imgs, 7.0 * np.ones((2, SIZE, SIZE, 3), np.float32)])
        plabels = np.concatenate([labels, np.zeros((2,), np.int32)])
        w = np.concatenate([np.ones(6), np.zeros(2)]).astype(np.float32)
        masked = eval_fn(params, variables,
                         {"images": pimgs, "labels": plabels, "weight": w})
        for k in full:
            assert abs(float(full[k]) - float(masked[k])) < 1e-5, k

    def test_full_holdout_covers_non_divisible_val_set(self, data_dir,
                                                       tmp_path):
        d, images, labels = data_dir
        val = str(tmp_path / "val")
        write_shards(val, images[:10], labels[:10], num_classes=CLASSES)
        from kubeflow_tpu.runtime.worker import train
        # global_batch 8 → eval_bs 8 → 10 records = 1 full + padded tail
        r = train(workload="resnet18", steps=1, global_batch=8,
                  data_dir=d, eval_data_dir=val, eval_every=1,
                  eval_batches=0, sync_every=1, seed=5)
        assert "top1" in r.final_metrics
        assert 0.0 <= r.final_metrics["top1"] <= 1.0

    def test_eval_data_dir_rejected_for_non_image_workload(self, data_dir):
        d, *_ = data_dir
        from kubeflow_tpu.runtime.worker import train
        with pytest.raises(ValueError, match="eval-data-dir"):
            train(workload="transformer", steps=1, global_batch=8,
                  eval_data_dir=d, eval_every=1, seed=0)

    def test_gang_env_eval_dir_ignored_when_eval_disabled(self, data_dir,
                                                          monkeypatch):
        # KFTPU_EVAL_DATA_DIR is set gang-wide; a transformer worker in
        # the gang with eval off must warn and run, not crash (ADVICE r4)
        d, *_ = data_dir
        monkeypatch.setenv("KFTPU_EVAL_DATA_DIR", d)
        from kubeflow_tpu.runtime.worker import train
        r = train(workload="transformer", steps=1, global_batch=8,
                  eval_every=0, sync_every=1, seed=0)
        assert r.steps == 1

    def test_gang_env_eval_dir_still_rejected_when_eval_enabled(
            self, data_dir, monkeypatch):
        d, *_ = data_dir
        monkeypatch.setenv("KFTPU_EVAL_DATA_DIR", d)
        from kubeflow_tpu.runtime.worker import train
        with pytest.raises(ValueError, match="eval-data-dir"):
            train(workload="transformer", steps=1, global_batch=8,
                  eval_every=1, seed=0)


@pytest.mark.slow
class TestCompileCache:
    """runtime/compile_cache.py: persistent XLA compilation cache wiring
    (BASELINE.md north-star #2 — startup→first-step on warm restarts)."""

    def test_operator_renders_cache_env_from_checkpoint_dir(self):
        from kubeflow_tpu.cluster import FakeCluster
        from kubeflow_tpu.controllers.runtime import Manager
        from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
        cluster = FakeCluster(auto_schedule=False, auto_run=False)
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create({
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "j", "namespace": "default"},
            "spec": {
                "checkpointDir": "/ckpt/run1",
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "worker", "image": "x"}]}}}},
            },
        })
        mgr.run_pending()
        pods = cluster.list("v1", "Pod", "default")
        env = {e["name"]: e["value"]
               for c in pods[0]["spec"]["containers"]
               for e in c.get("env", [])}
        # default: cache rides the checkpoint volume
        assert env["KFTPU_COMPILE_CACHE_DIR"] == \
            "/ckpt/run1/.jax-compile-cache"

    def test_explicit_compile_cache_dir_roundtrips_and_wins(self):
        from kubeflow_tpu.api.trainingjob import TrainingJob
        m = {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "j", "namespace": "default"},
            "spec": {
                "checkpointDir": "/ckpt", "compileCacheDir": "/fast/cache",
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "w", "image": "x"}]}}}},
            },
        }
        job = TrainingJob.from_manifest(m)
        assert job.compile_cache_dir == "/fast/cache"
        assert job.to_manifest()["spec"]["compileCacheDir"] == "/fast/cache"

    def test_worker_populates_cache_dir(self, tmp_path, monkeypatch):
        import os
        cache = str(tmp_path / "jaxcache")
        monkeypatch.setenv("KFTPU_COMPILE_CACHE_DIR", cache)
        # a warm process compiles this tiny model in <1s, under the
        # persistence threshold — pin it to 0 so the assertion is not
        # an ordering flake
        monkeypatch.setenv("KFTPU_COMPILE_CACHE_MIN_SECS", "0")
        from kubeflow_tpu.runtime.worker import train
        train(workload="resnet18", steps=1, global_batch=8, sync_every=1,
              workload_kwargs={"image_size": 16, "num_classes": 4}, seed=0)
        assert os.path.isdir(cache) and os.listdir(cache), \
            "train step executable was not persisted"

    def test_enable_is_noop_without_env(self, monkeypatch):
        from kubeflow_tpu.runtime.compile_cache import (
            enable_compilation_cache)
        monkeypatch.delenv("KFTPU_COMPILE_CACHE_DIR", raising=False)
        assert enable_compilation_cache() is None
