"""Unit tests for the core API types (k8s object model, topology, jobs, KfDef).

Mirrors the reference's API-type round-trip tests
(bootstrap/.../application_types_test.go) and CRD validation behavior
(tf-job-operator.libsonnet:14-46 Chief max 1; mpi-operator.libsonnet:27-77
oneOf validation).
"""

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.api.kfdef import KfDef, KfDefSpec, PLATFORM_GCP
from kubeflow_tpu.api.topology import (
    TopologyContract, parse_topology, render_contracts,
)
from kubeflow_tpu.api.trainingjob import ShardingSpec, TrainingJob


class TestK8sModel:
    def test_make_and_keys(self):
        obj = k8s.make("v1", "Service", "svc", "ns", labels={"a": "b"})
        assert k8s.key_of(obj) == ("v1", "Service", "ns", "svc")
        assert k8s.labels_of(obj) == {"a": "b"}

    def test_selector(self):
        obj = k8s.make("v1", "Pod", "p", labels={"app": "x", "tier": "web"})
        assert k8s.matches_selector(obj, {"app": "x"})
        assert not k8s.matches_selector(obj, {"app": "y"})
        assert k8s.selector_from({"matchLabels": {"a": "1"}}) == {"a": "1"}

    def test_owner_refs(self):
        owner = k8s.make("v1", "Job", "j", "ns")
        owner["metadata"]["uid"] = "u1"
        child = k8s.make("v1", "Pod", "p", "ns")
        k8s.set_owner(child, owner)
        assert k8s.is_owned_by(child, owner)

    def test_conditions_upsert(self):
        obj = {}
        k8s.set_condition(obj, k8s.Condition("Ready", "False", reason="init"))
        t0 = obj["status"]["conditions"][0]["lastTransitionTime"]
        k8s.set_condition(obj, k8s.Condition("Ready", "False", reason="still"))
        assert obj["status"]["conditions"][0]["lastTransitionTime"] == t0
        k8s.set_condition(obj, k8s.Condition("Ready", "True"))
        assert len(obj["status"]["conditions"]) == 1
        assert k8s.condition_true(obj, "Ready")

    def test_param_substitution_preserves_types(self):
        out = k8s.substitute_params(
            {"replicas": "$(n)", "img": "repo/$(name):v1"}, {"n": 3, "name": "tpu"})
        assert out == {"replicas": 3, "img": "repo/tpu:v1"}

    def test_deep_merge(self):
        merged = k8s.deep_merge({"a": {"b": 1, "c": 2}}, {"a": {"c": 3}, "d": 4})
        assert merged == {"a": {"b": 1, "c": 3}, "d": 4}

    def test_sort_for_apply(self):
        objs = [k8s.make("apps/v1", "Deployment", "d"),
                k8s.make("v1", "Namespace", "ns"),
                k8s.make("apiextensions.k8s.io/v1", "CustomResourceDefinition", "crd")]
        kinds = [o["kind"] for o in k8s.sort_for_apply(objs)]
        assert kinds == ["Namespace", "CustomResourceDefinition", "Deployment"]


class TestTopology:
    def test_parse_v5e_32(self):
        t = parse_topology("v5e-32")
        assert t.num_chips == 32
        assert t.num_hosts == 8
        assert t.ici_mesh == (4, 8)

    def test_single_chip(self):
        t = parse_topology("v5e-1")
        assert t.num_hosts == 1 and t.chips_per_host == 1

    def test_invalid(self):
        with pytest.raises(ValueError):
            parse_topology("v5e-13")
        with pytest.raises(ValueError):
            parse_topology("h100-8")

    def test_contract_render(self):
        topo = parse_topology("v5e-32")
        contracts = render_contracts("train", "kubeflow", topo, num_slices=2)
        assert len(contracts) == 16  # 2 slices x 8 hosts
        assert contracts[0].process_id == 0 and contracts[-1].process_id == 15
        assert contracts[9].slice_id == 1
        env = contracts[3].to_env()
        rt = TopologyContract.from_env(env)
        assert rt.process_id == 3
        assert rt.slice_topology.num_chips == 32
        assert "train-worker-0-0" in rt.coordinator_address


class TestShardingSpec:
    def test_wildcard_fill(self):
        s = ShardingSpec(data=-1, tensor=4)
        sizes = s.resolve(32)
        assert sizes["data"] == 8 and sizes["tensor"] == 4

    def test_exact_product(self):
        s = ShardingSpec(data=2, fsdp=2, tensor=2, pipeline=1, sequence=2, expert=1)
        assert s.resolve(16)["sequence"] == 2

    def test_mismatch_raises(self):
        with pytest.raises(ValueError):
            ShardingSpec(data=3, tensor=3).resolve(8)


class TestTrainingJob:
    def _tpujob(self, **spec_extra):
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1",
            "kind": "TPUJob",
            "metadata": {"name": "mnist", "namespace": "kubeflow"},
            "spec": {
                "replicaSpecs": {
                    "TPU": {"tpuTopology": "v5e-32",
                            "template": {"spec": {"containers": [{"name": "jax"}]}}},
                },
                **spec_extra,
            },
        }

    def test_tpujob_parse(self):
        job = TrainingJob.from_manifest(self._tpujob())
        assert job.tpu_spec.pod_count == 8
        assert job.total_pods() == 8
        assert job.run_policy.gang_scheduling

    def test_tfjob_with_tpu_replica(self):
        m = {
            "apiVersion": "kubeflow.org/v1beta2", "kind": "TFJob",
            "metadata": {"name": "tf-cnn"},
            "spec": {"tfReplicaSpecs": {
                "Chief": {"replicas": 1, "template": {}},
                "TPU": {"tpuTopology": "v5e-8", "template": {}},
            }},
        }
        job = TrainingJob.from_manifest(m)
        assert job.replica_specs["TPU"].topology.num_hosts == 2
        assert job.total_pods() == 3

    def test_chief_max_one(self):
        m = {"apiVersion": "kubeflow.org/v1beta2", "kind": "TFJob",
             "metadata": {"name": "bad"},
             "spec": {"tfReplicaSpecs": {"Chief": {"replicas": 2, "template": {}}}}}
        with pytest.raises(ValueError, match="at most one Chief"):
            TrainingJob.from_manifest(m)

    def test_mpijob_topology_shorthand(self):
        m = {"apiVersion": "kubeflow.org/v1alpha1", "kind": "MPIJob",
             "metadata": {"name": "allreduce"},
             "spec": {"tpuTopology": "v5e-16", "template": {}}}
        job = TrainingJob.from_manifest(m)
        assert job.tpu_spec.pod_count == 4

    def test_mpijob_requires_oneof(self):
        m = {"apiVersion": "kubeflow.org/v1alpha1", "kind": "MPIJob",
             "metadata": {"name": "bad"}, "spec": {}}
        with pytest.raises(ValueError, match="one of"):
            TrainingJob.from_manifest(m)

    def test_tpu_requires_topology(self):
        m = self._tpujob()
        del m["spec"]["replicaSpecs"]["TPU"]["tpuTopology"]
        with pytest.raises(ValueError, match="tpuTopology"):
            TrainingJob.from_manifest(m)

    def test_sharding_validated_at_admission(self):
        m = self._tpujob(sharding={"data": 5, "tensor": 5})
        with pytest.raises(ValueError):
            TrainingJob.from_manifest(m)

    def test_roundtrip(self):
        job = TrainingJob.from_manifest(self._tpujob())
        m2 = job.to_manifest()
        job2 = TrainingJob.from_manifest(m2)
        assert job2.tpu_spec.topology.name == "v5e-32"


class TestKfDef:
    def test_save_load_roundtrip(self, tmp_path):
        kf = KfDef(name="kf", spec=KfDefSpec(app_dir=str(tmp_path)))
        kf.set_condition("Available", "True", reason="deployed")
        kf.save()
        kf2 = KfDef.load(str(tmp_path))
        assert kf2.name == "kf"
        assert kf2.spec.components == kf.spec.components
        assert kf2.conditions[0].type == "Available"

    def test_validate_gcp_requires_project(self):
        kf = KfDef(name="kf", spec=KfDefSpec(platform=PLATFORM_GCP))
        with pytest.raises(ValueError, match="project"):
            kf.validate()

    def test_load_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            KfDef.load(str(tmp_path / "nope"))
