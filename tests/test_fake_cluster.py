"""Tests for the in-memory apiserver + gang scheduler (the envtest analog)."""

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.cluster import (AlreadyExistsError, ConflictError,
                                  FakeCluster, NotFoundError)
from kubeflow_tpu.cluster.apply import apply_manifests, delete_manifests
from kubeflow_tpu.cluster.fake import POD_GROUP_LABEL, TPU_RESOURCE


def make_pod(name, ns="default", chips=0, group=None, min_member=None,
             node_selector=None):
    pod = k8s.make("v1", "Pod", name, ns)
    container = {"name": "main", "image": "img"}
    if chips:
        container["resources"] = {"limits": {TPU_RESOURCE: chips}}
    pod["spec"] = {"containers": [container]}
    if node_selector:
        pod["spec"]["nodeSelector"] = node_selector
    if group:
        pod["metadata"]["labels"] = {POD_GROUP_LABEL: group}
        pod["metadata"]["annotations"] = {
            "scheduling.kubeflow.org/min-member": str(min_member or 1)}
    return pod


class TestCrud:
    def test_create_get_uid_rv(self):
        c = FakeCluster()
        c.create(k8s.make("v1", "ConfigMap", "cm", "ns1"))
        obj = c.get("v1", "ConfigMap", "ns1", "cm")
        assert obj["metadata"]["uid"].startswith("uid-")
        with pytest.raises(AlreadyExistsError):
            c.create(k8s.make("v1", "ConfigMap", "cm", "ns1"))

    def test_update_conflict(self):
        c = FakeCluster()
        c.create(k8s.make("v1", "ConfigMap", "cm"))
        a = c.get("v1", "ConfigMap", "default", "cm")
        b = c.get("v1", "ConfigMap", "default", "cm")
        a["data"] = {"x": "1"}
        c.update(a)
        b["data"] = {"x": "2"}
        with pytest.raises(ConflictError):
            c.update(b)

    def test_status_subresource_preserves_spec(self):
        c = FakeCluster()
        c.create(k8s.make("v1", "Pod", "p", spec={"containers": []}))
        p = c.get("v1", "Pod", "default", "p")
        p["status"] = {"phase": "Running"}
        del p["spec"]
        c.update_status(p)
        stored = c.get("v1", "Pod", "default", "p")
        assert stored["spec"] == {"containers": []}
        assert stored["status"]["phase"] == "Running"

    def test_list_selector_and_namespace(self):
        c = FakeCluster()
        c.create(k8s.make("v1", "Pod", "a", "ns1", labels={"app": "x"}))
        c.create(k8s.make("v1", "Pod", "b", "ns1", labels={"app": "y"}))
        c.create(k8s.make("v1", "Pod", "a", "ns2", labels={"app": "x"}))
        assert len(c.list("v1", "Pod")) == 3
        assert len(c.list("v1", "Pod", "ns1")) == 2
        assert len(c.list("v1", "Pod", selector={"app": "x"})) == 2

    def test_cascade_delete(self):
        c = FakeCluster()
        owner = c.create(k8s.make("batch/v1", "Job", "j", "ns"))
        child = k8s.make("v1", "Pod", "p", "ns")
        k8s.set_owner(child, owner)
        c.create(child)
        grandchild = k8s.make("v1", "ConfigMap", "g", "ns")
        k8s.set_owner(grandchild, c.get("v1", "Pod", "ns", "p"))
        c.create(grandchild)
        c.delete("batch/v1", "Job", "ns", "j")
        with pytest.raises(NotFoundError):
            c.get("v1", "Pod", "ns", "p")
        with pytest.raises(NotFoundError):
            c.get("v1", "ConfigMap", "ns", "g")

    def test_watch_delivers_filtered(self):
        c = FakeCluster()
        w = c.watch("v1", "Pod")
        c.create(k8s.make("v1", "Pod", "p"))
        c.create(k8s.make("v1", "Service", "s"))
        ev = w.get(timeout=0.1)
        assert ev.type == "ADDED" and ev.obj["kind"] == "Pod"
        assert w.get(timeout=0.01) is None

    def test_apply_create_or_update(self):
        c = FakeCluster()
        cm = k8s.make("v1", "ConfigMap", "cm")
        cm["data"] = {"a": "1"}
        c.apply(cm)
        cm2 = k8s.make("v1", "ConfigMap", "cm")
        cm2["data"] = {"a": "2"}
        c.apply(cm2)
        assert c.get("v1", "ConfigMap", "default", "cm")["data"] == {"a": "2"}


class TestGangScheduling:
    def test_gang_binds_all_or_nothing(self):
        c = FakeCluster(auto_run=False)
        c.add_tpu_slice_nodes("v5e-8")  # 2 nodes x 4 chips
        sel = {"cloud.google.com/gke-tpu-topology": "v5e-8"}
        for i in range(2):
            c.create(make_pod(f"w{i}", chips=4, group="g1", min_member=3,
                              node_selector=sel))
        c.schedule()
        # only 2 of min-member 3 exist: nothing binds
        assert all(not p["spec"].get("nodeName") for p in c.list("v1", "Pod"))
        c.create(make_pod("w2", chips=4, group="g1", min_member=3,
                          node_selector=sel))
        c.schedule()
        # 3 pods x 4 chips > 8 chips capacity: still nothing binds (atomic)
        assert all(not p["spec"].get("nodeName") for p in c.list("v1", "Pod"))

    def test_gang_binds_when_capacity_fits(self):
        c = FakeCluster(auto_run=False)
        c.add_tpu_slice_nodes("v5e-8")
        for i in range(2):
            c.create(make_pod(f"w{i}", chips=4, group="g1", min_member=2))
        c.schedule()
        nodes = {p["spec"].get("nodeName") for p in c.list("v1", "Pod")}
        assert len(nodes) == 2 and None not in nodes  # one pod per host

    def test_singles_schedule_independently(self):
        c = FakeCluster(auto_run=False)
        c.add_node("cpu-1", {"cpu": 4})
        c.create(make_pod("solo"))
        c.schedule()
        assert c.get("v1", "Pod", "default", "solo")["spec"]["nodeName"] == "cpu-1"

    def test_tick_runs_pods(self):
        c = FakeCluster()
        c.add_node("cpu-1", {"cpu": 4})
        c.create(make_pod("solo"))
        c.tick()
        assert c.get("v1", "Pod", "default", "solo")["status"]["phase"] == "Running"

    def test_node_selector_respected(self):
        c = FakeCluster(auto_run=False)
        c.add_node("wrong", {TPU_RESOURCE: 8})
        c.create(make_pod("p", chips=4,
                          node_selector={"cloud.google.com/gke-tpu-topology": "v5e-8"}))
        c.schedule()
        assert not c.get("v1", "Pod", "default", "p")["spec"].get("nodeName")


class TestApplyEngine:
    def test_apply_ordering_and_namespace_defaulting(self):
        c = FakeCluster()
        objs = [k8s.make("apps/v1", "Deployment", "d"),
                k8s.make("v1", "Namespace", "kubeflow")]
        res = apply_manifests(c, objs, namespace="kubeflow", sleep=lambda s: None)
        assert res.ok
        d = c.get("apps/v1", "Deployment", "kubeflow", "d")
        assert d["metadata"]["namespace"] == "kubeflow"

    def test_apply_retry_then_failure_recorded(self):
        c = FakeCluster()

        class Boom(FakeCluster):
            def apply(self, obj):
                raise RuntimeError("apiserver down")

        res = apply_manifests(Boom(), [k8s.make("v1", "ConfigMap", "cm")],
                              attempts=2, sleep=lambda s: None)
        assert not res.ok and len(res.failed) == 1

    def test_delete_manifests(self):
        c = FakeCluster()
        objs = [k8s.make("v1", "Namespace", "ns"),
                k8s.make("v1", "ConfigMap", "cm", "ns")]
        apply_manifests(c, objs, sleep=lambda s: None)
        delete_manifests(c, objs)
        assert c.list("v1", "ConfigMap") == []
