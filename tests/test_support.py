"""Support-component tests (SURVEY.md §2.7): metric-collector, spartakus,
echo-server, https-redirect."""

import json
import urllib.request

import pytest

from kubeflow_tpu.support.deploy_prober import DeployProber

from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.support.echo_server import EchoServer
from kubeflow_tpu.support.https_redirect import RedirectServer
from kubeflow_tpu.support.metric_collector import (AvailabilityProber,
                                                   MetricsServer)
from kubeflow_tpu.support.spartakus import (DISABLE_ENV, UsageReporter,
                                            collect_facts)


class TestMetricCollector:
    def test_probe_updates_gauge(self):
        statuses = [200, 503, 200]
        calls = []

        def fetch(url, headers, timeout):
            calls.append(headers)
            return statuses[len(calls) - 1]

        prober = AvailabilityProber(
            "http://kf.example/healthz", fetch=fetch,
            header_provider=lambda: {"Authorization": "Bearer tok"})
        assert prober.probe() is True
        assert prober.available == 1
        assert prober.probe() is False
        assert prober.available == 0
        assert prober.failures == 1
        assert prober.probe() is True
        assert calls[0]["Authorization"] == "Bearer tok"

    def test_unreachable_endpoint_is_recorded_not_raised(self):
        def fetch(url, headers, timeout):
            raise OSError("connection refused")

        prober = AvailabilityProber("http://down.example", fetch=fetch)
        assert prober.probe() is False
        assert "connection refused" in prober.last_error

    def test_metrics_endpoint_prometheus_format(self):
        prober = AvailabilityProber("http://x", fetch=lambda *a: 200)
        prober.probe()
        server = MetricsServer(prober)
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as r:
                text = r.read().decode()
            assert "kubeflow_availability 1" in text
            assert "# TYPE kubeflow_availability gauge" in text
        finally:
            server.stop()


class TestDeployProber:
    """The click-to-deploy prober analog (testing/test_deploy_app.py):
    a full deploy drill against a LIVE bootstrap server, with Prometheus
    counters — CI doubling as availability monitoring."""

    @pytest.fixture
    def bootstrap(self, tmp_path):
        from kubeflow_tpu.kfctl.bootstrap_server import BootstrapServer
        server = BootstrapServer(str(tmp_path / "apps"))
        server.start()
        yield f"http://127.0.0.1:{server.port}"
        server.stop()

    def test_full_drill_success_and_cleanup(self, bootstrap):
        import urllib.request
        prober = DeployProber(bootstrap, app_name="drill",
                              components=["access-management"])
        assert prober.probe() is True
        assert prober.successes == 1 and prober.failures == 0
        text = prober.metrics_text()
        assert "deploy_prober_last_cycle_ok 1" in text
        assert "deploy_prober_success_total 1" in text
        assert "deploy_prober_last_cycle_seconds" in text
        # the drill deleted its app: the next cycle can run (no 409)
        with urllib.request.urlopen(f"{bootstrap}/kfctl/apps") as r:
            assert json.loads(r.read())["apps"] == []
        assert prober.probe() is True
        assert prober.successes == 2

    def test_poll_window_scales_with_probe_interval(self):
        # unset poll_tries scales with the probe cadence (ADVICE r5):
        # interval/2 worth of polls, clamped to [2s, 120s] of window
        fast = DeployProber("http://x", interval_s=10.0)
        assert fast.poll_tries == int(5.0 / 0.2)          # 25 polls
        slow = DeployProber("http://x", interval_s=600.0)
        assert slow.poll_tries == int(120.0 / 0.2)        # clamped cap
        tiny = DeployProber("http://x", interval_s=0.5)
        assert tiny.poll_tries == int(2.0 / 0.2)          # clamped floor
        # explicit values always win over scaling
        pinned = DeployProber("http://x", poll_tries=3,
                              poll_sleep_s=1.5, interval_s=600.0)
        assert pinned.poll_tries == 3 and pinned.poll_sleep_s == 1.5

    def test_poll_flags_reach_the_prober(self, bootstrap, monkeypatch):
        # prober_main wiring: --poll-tries/--poll-sleep reach the
        # DeployProber main() constructs (run_forever stubbed out so
        # the entrypoint returns instead of looping)
        from kubeflow_tpu.support import deploy_prober as dp
        built = {}
        monkeypatch.setattr(
            dp.DeployProber, "run_forever",
            lambda self, interval_s, stop=None: built.update(
                tries=self.poll_tries, sleep=self.poll_sleep_s,
                interval=interval_s))
        assert dp.main(["--url", bootstrap, "--interval", "30",
                        "--poll-tries", "4", "--poll-sleep", "0.1",
                        "--metrics-host", "127.0.0.1",
                        "--metrics-port", "0"]) == 0
        assert built == {"tries": 4, "sleep": 0.1, "interval": 30.0}

    def test_failure_is_recorded_not_raised(self):
        # nothing listens here: the drill fails, the counter records it
        prober = DeployProber("http://127.0.0.1:9", timeout_s=0.5)
        assert prober.probe() is False
        assert prober.failures == 1 and prober.last_ok == 0
        assert prober.last_error
        assert "deploy_prober_last_cycle_ok 0" in prober.metrics_text()

    def test_metrics_served_over_http(self, bootstrap):
        import urllib.request
        from kubeflow_tpu.support.metric_collector import MetricsServer
        prober = DeployProber(bootstrap, app_name="drill2")
        prober.probe()
        server = MetricsServer(prober)
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as r:
                body = r.read().decode()
            assert "deploy_prober_success_total 1" in body
        finally:
            server.stop()


class TestSpartakus:
    @pytest.fixture
    def cluster(self):
        c = FakeCluster()
        c.add_tpu_slice_nodes("v5e-8")
        c.create({"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "alice"}})
        return c

    def test_facts_are_anonymized_counts(self, cluster):
        facts = collect_facts(cluster, usage_id=42)
        assert facts["usageId"] == 42
        assert facts["nodes"] == 2
        assert facts["tpuChips"] == 8
        assert facts["tpuTopologies"] == {"v5e-8": 2}
        # nothing resembling a name leaves the cluster
        assert "alice" not in json.dumps(facts)

    def test_report_once_uses_sink(self, cluster):
        sent = []
        reporter = UsageReporter(cluster, sink=sent.append, usage_id=7)
        payload = reporter.report_once()
        assert sent == [payload]
        assert payload["usageId"] == 7

    def test_env_opt_out(self, cluster, monkeypatch):
        monkeypatch.setenv(DISABLE_ENV, "1")
        reporter = UsageReporter(cluster, sink=lambda p: 1 / 0)
        assert not reporter.enabled
        assert reporter.report_once() is None

    def test_sink_failure_never_raises(self, cluster):
        def bad_sink(p):
            raise OSError("no route")

        reporter = UsageReporter(cluster, sink=bad_sink)
        assert reporter.report_once() is None  # logged, not raised


class TestEchoAndRedirect:
    def test_echo_roundtrip(self):
        server = EchoServer()
        port = server.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/some/path?q=1",
                data=b"hello", headers={"X-Test": "v"})
            with urllib.request.urlopen(req) as r:
                body = json.loads(r.read())
            assert body["method"] == "POST"
            assert body["path"] == "/some/path?q=1"
            assert body["body"] == "hello"
            assert body["headers"]["X-Test"] == "v"
        finally:
            server.stop()

    def test_redirect_preserves_path(self):
        server = RedirectServer(target_host="kubeflow.example.com")
        port = server.start()
        try:
            class NoRedirect(urllib.request.HTTPRedirectHandler):
                def redirect_request(self, *a, **k):
                    return None

            opener = urllib.request.build_opener(NoRedirect)
            try:
                opener.open(f"http://127.0.0.1:{port}/a/b?x=1")
                raise AssertionError("expected redirect error")
            except urllib.error.HTTPError as e:
                assert e.code == 301
                assert e.headers["Location"] == \
                    "https://kubeflow.example.com/a/b?x=1"
        finally:
            server.stop()


@pytest.mark.slow
class TestTensorboardEvents:
    """The dependency-free event writer must produce files the REAL
    TensorBoard reader accepts (format cross-validation, not a mirror of
    our own encoder)."""

    def _read(self, logdir):
        from tensorboard.backend.event_processing.event_file_loader import (
            EventFileLoader)
        import glob
        out = []
        for path in sorted(glob.glob(f"{logdir}/events.out.tfevents.*")):
            for ev in EventFileLoader(path).Load():
                for v in getattr(ev.summary, "value", []):
                    # TB's compat layer migrates simple_value → tensor
                    val = (v.tensor.float_val[0]
                           if v.tensor.float_val else v.simple_value)
                    out.append((ev.step, v.tag, round(val, 5)))
        return out

    def test_roundtrip_against_real_tensorboard_reader(self, tmp_path):
        from kubeflow_tpu.utils.tbevents import EventWriter
        with EventWriter(str(tmp_path)) as w:
            w.add_scalar("loss", 2.5, step=1)
            w.add_scalars({"loss": 1.25, "accuracy": 0.5}, step=2)
        got = self._read(str(tmp_path))
        assert (1, "loss", 2.5) in got
        assert (2, "loss", 1.25) in got
        assert (2, "accuracy", 0.5) in got

    def test_crc32c_known_vectors(self):
        from kubeflow_tpu.utils.tbevents import _crc32c
        # RFC 3720 test vectors
        assert _crc32c(b"") == 0x0
        assert _crc32c(b"123456789") == 0xE3069283
        assert _crc32c(bytes(32)) == 0x8A9136AA

    def test_worker_writes_tb_events(self, tmp_path):
        from kubeflow_tpu.runtime.worker import train
        tb = str(tmp_path / "tb")
        train(workload="transformer", steps=2, global_batch=8,
              sync_every=1, tensorboard_dir=tb, eval_every=2,
              eval_batches=1, workload_kwargs={})
        got = self._read(tb)
        tags = {t for _, t, _ in got}
        assert "loss" in tags
        assert "throughput/examples_per_sec" in tags
        assert "eval/perplexity" in tags
        # eval events landed at the eval step
        assert any(s == 2 and t == "eval/perplexity" for s, t, _ in got)
