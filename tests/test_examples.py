"""The examples/ directory stays truthful: YAMLs match their builders
byte-for-byte, and the DSL example compiles, schedules, and runs."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "examples"))


def test_rendered_yamls_in_sync():
    import regenerate
    for component, fname, params in regenerate.EXAMPLES:
        with open(os.path.join(REPO, "examples", fname)) as f:
            on_disk = f.read()
        assert on_disk == regenerate.render(component, params), \
            f"{fname} is stale — run python examples/regenerate.py"


def test_pipeline_example_compiles_and_schedules():
    import pipeline_example
    p = pipeline_example.build()
    wf = p.compile()
    names = [t["name"] for t in wf["spec"]["templates"]]
    assert names == ["main", "prep", "train", "report"]
    # run-unique launch name → schedulable without AlreadyExists
    swf = p.schedule("0 2 * * *")
    assert swf["kind"] == "ScheduledWorkflow"


def test_pipeline_example_runs_end_to_end():
    from kubeflow_tpu.api import k8s
    from kubeflow_tpu.cluster import FakeCluster
    from kubeflow_tpu.controllers.runtime import Manager
    from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
    from kubeflow_tpu.workflows.engine import WorkflowReconciler
    import pipeline_example
    cluster = FakeCluster()
    cluster.add_node("cpu-0", {"cpu": 96, "memory": 2 ** 36})
    cluster.add_tpu_slice_nodes("v5e-8")
    mgr = Manager(cluster)
    mgr.add(WorkflowReconciler())
    mgr.add(TrainingJobReconciler("TPUJob"))
    pipeline_example.build().submit(cluster, steps="7")
    for _ in range(8):
        mgr.run_pending()
        cluster.tick()
        for pod in cluster.list("v1", "Pod", "kubeflow"):
            if pod.get("status", {}).get("phase") == "Running":
                cluster.set_pod_phase("kubeflow", k8s.name_of(pod),
                                      "Succeeded")
        mgr.run_pending()
    wf = cluster.get("argoproj.io/v1alpha1", "Workflow", "kubeflow",
                     "train-and-report")
    assert wf["status"]["phase"] == "Succeeded", wf["status"]
    job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                      "job-train-and-report")
    cmd = job["spec"]["replicaSpecs"]["TPU"]["template"]["spec"][
        "containers"][0]["command"]
    assert cmd[-1] == "7"  # the run parameter reached the worker
