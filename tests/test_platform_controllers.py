"""Platform controllers: notebook, profile, admission webhook, gatekeeper.

Envtest-style coverage mirroring the reference's controller tests
(profile_controller_test.go reconcile-assertion pattern, SURVEY.md §4
tier 2; admission-webhook merge/conflict logic main.go:69-316;
gatekeeper session table AuthServer.go:36-153).
"""

import urllib.request

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.controllers.admission import (PodDefaultConflict,
                                                PodDefaultsWebhook,
                                                apply_pod_defaults,
                                                select_pod_defaults)
from kubeflow_tpu.controllers.notebook import NotebookReconciler
from kubeflow_tpu.controllers.profile import ProfileReconciler
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.statefulset import StatefulSetReconciler
from kubeflow_tpu.webapps.gatekeeper import (Gatekeeper, GatekeeperServer,
                                             SessionStore)


@pytest.fixture(params=["direct", "http"])
def env(request):
    """Runs twice: FakeCluster direct and over the HTTP wire
    (client → apiserver → FakeCluster; see _http_env.py)."""
    from _http_env import make_env_cluster
    cluster, cleanup = make_env_cluster(request.param)
    cluster.add_node("cpu-0", {"cpu": 96, "memory": 2 ** 36})
    mgr = Manager(cluster)
    mgr.add(StatefulSetReconciler())
    mgr.add(NotebookReconciler())
    mgr.add(ProfileReconciler())
    yield cluster, mgr
    for c in mgr.controllers:
        c.stop()
    cleanup()


def notebook_manifest(name="nb", image="jupyter:latest", **resources):
    container = {"name": "notebook", "image": image}
    if resources:
        container["resources"] = resources
    return {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "Notebook",
        "metadata": {"name": name, "namespace": "alice"},
        "spec": {"template": {"spec": {"containers": [container]}}},
    }


class TestNotebookController:
    def test_creates_sts_service_virtualservice(self, env):
        cluster, mgr = env
        cluster.create(notebook_manifest())
        mgr.run_pending()
        sts = cluster.get("apps/v1", "StatefulSet", "alice", "nb")
        assert sts["spec"]["replicas"] == 1
        tmpl = sts["spec"]["template"]
        assert tmpl["metadata"]["labels"]["notebook-name"] == "nb"
        assert tmpl["spec"]["securityContext"]["fsGroup"] == 100
        svc = cluster.get("v1", "Service", "alice", "nb")
        assert svc["spec"]["ports"][0]["targetPort"] == 8888
        vs = cluster.get("networking.istio.io/v1alpha3", "VirtualService",
                         "alice", "notebook-nb")
        prefix = vs["spec"]["http"][0]["match"][0]["uri"]["prefix"]
        assert prefix == "/notebook/alice/nb/"
        # all children owned → cascade GC
        for obj in (sts, svc, vs):
            assert obj["metadata"]["ownerReferences"][0]["kind"] == "Notebook"

    def test_sts_controller_creates_pod_and_status_flows(self, env):
        cluster, mgr = env
        cluster.create(notebook_manifest())
        mgr.run_pending()
        cluster.tick()   # pod scheduled + running
        mgr.run_pending()
        pod = cluster.get("v1", "Pod", "alice", "nb-0")
        assert pod["status"]["phase"] == "Running"
        nb = cluster.get("kubeflow.org/v1alpha1", "Notebook", "alice", "nb")
        assert nb["status"]["readyReplicas"] == 1
        assert k8s.condition_true(nb, "Ready")
        assert "running" in nb["status"]["containerState"]

    def test_tpu_notebook_schedules_on_tpu_pool(self, env):
        # placement via the extended resource, not a hardcoded accelerator
        # selector (which would pin notebooks to one TPU generation)
        cluster, mgr = env
        cluster.add_tpu_slice_nodes("v5p-8")
        cluster.create(notebook_manifest(limits={"google.com/tpu": 4}))
        mgr.run_pending()
        cluster.tick()
        pod = cluster.get("v1", "Pod", "alice", "nb-0")
        assert "nodeSelector" not in pod["spec"]
        assert pod["spec"]["nodeName"].startswith("tpu-pool")

    def test_notebook_image_edit_rolls_the_pod(self, env):
        cluster, mgr = env
        cluster.create(notebook_manifest(image="jupyter:v1"))
        mgr.run_pending()
        cluster.tick()
        mgr.run_pending()
        nb = cluster.get("kubeflow.org/v1alpha1", "Notebook", "alice", "nb")
        nb["spec"]["template"]["spec"]["containers"][0]["image"] = \
            "jupyter:v2"
        cluster.update(nb)
        mgr.run_pending()
        cluster.tick()
        mgr.run_pending()
        pod = cluster.get("v1", "Pod", "alice", "nb-0")
        assert pod["spec"]["containers"][0]["image"] == "jupyter:v2"

    def test_delete_cascades(self, env):
        cluster, mgr = env
        cluster.create(notebook_manifest())
        mgr.run_pending()
        cluster.delete("kubeflow.org/v1alpha1", "Notebook", "alice", "nb")
        assert cluster.get_or_none("apps/v1", "StatefulSet", "alice",
                                   "nb") is None
        assert cluster.get_or_none("v1", "Service", "alice", "nb") is None


class TestStatefulSetController:
    def test_scale_down_removes_high_ordinals(self, env):
        cluster, mgr = env
        sts = {
            "apiVersion": "apps/v1", "kind": "StatefulSet",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"replicas": 3,
                     "selector": {"matchLabels": {"app": "web"}},
                     "template": {"spec": {"containers": [
                         {"name": "c", "image": "i"}]}}},
        }
        cluster.create(sts)
        mgr.run_pending()
        assert len(cluster.list("v1", "Pod", "default")) == 3
        stored = cluster.get("apps/v1", "StatefulSet", "default", "web")
        stored["spec"]["replicas"] = 1
        cluster.update(stored)
        mgr.run_pending()
        names = {k8s.name_of(p) for p in cluster.list("v1", "Pod", "default")}
        assert names == {"web-0"}


class TestProfileController:
    def test_provisions_namespace_sas_bindings(self, env):
        cluster, mgr = env
        cluster.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "Profile",
            "metadata": {"name": "team-ml"},
            "spec": {"owner": {"kind": "User", "name": "alice@example.com"},
                     "resourceQuotaSpec": {"hard": {"cpu": "8"}}},
        })
        mgr.run_pending()
        ns = cluster.get("v1", "Namespace", "", "team-ml")
        assert ns["metadata"]["annotations"]["owner"] == "alice@example.com"
        for sa in ("default-editor", "default-viewer"):
            assert cluster.get("v1", "ServiceAccount", "team-ml", sa)
        rb = cluster.get("rbac.authorization.k8s.io/v1", "RoleBinding",
                         "team-ml", "namespaceAdmin")
        assert rb["subjects"][0]["name"] == "alice@example.com"
        quota = cluster.get("v1", "ResourceQuota", "team-ml",
                            "kf-resource-quota")
        assert quota["spec"]["hard"]["cpu"] == "8"
        profile = cluster.get("kubeflow.org/v1alpha1", "Profile", "",
                              "team-ml")
        assert k8s.condition_true(profile, "Ready")

    def test_dropping_quota_spec_prunes_the_quota(self, env):
        cluster, mgr = env
        cluster.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "Profile",
            "metadata": {"name": "team-ml"},
            "spec": {"owner": {"kind": "User", "name": "a@x.com"},
                     "resourceQuotaSpec": {"hard": {"cpu": "8"}}},
        })
        mgr.run_pending()
        assert cluster.get("v1", "ResourceQuota", "team-ml",
                           "kf-resource-quota")
        profile = cluster.get("kubeflow.org/v1alpha1", "Profile", "",
                              "team-ml")
        del profile["spec"]["resourceQuotaSpec"]
        cluster.update(profile)
        mgr.run_pending()
        assert cluster.get_or_none("v1", "ResourceQuota", "team-ml",
                                   "kf-resource-quota") is None


def pod_default(name, selector, **spec):
    return {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "PodDefault",
        "metadata": {"name": name, "namespace": "alice",
                     "resourceVersion": "1"},
        "spec": {"selector": {"matchLabels": selector}, **spec},
    }


def pod(labels=None):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "p", "namespace": "alice",
                     "labels": labels or {}},
        "spec": {"containers": [{"name": "main", "image": "i"}]},
    }


class TestPodDefaults:
    def test_selection_by_label(self):
        pds = [pod_default("a", {"inject": "yes"}),
               pod_default("b", {"other": "x"})]
        assert [k8s.name_of(p) for p in
                select_pod_defaults(pod({"inject": "yes"}), pds)] == ["a"]
        assert select_pod_defaults(pod({}), pds) == []

    def test_merge_env_volumes_mounts(self):
        pds = [pod_default(
            "gcp-creds", {"inject": "yes"},
            env=[{"name": "GOOGLE_APPLICATION_CREDENTIALS",
                  "value": "/secret/key.json"}],
            volumeMounts=[{"name": "creds", "mountPath": "/secret"}],
            volumes=[{"name": "creds", "secret": {"secretName": "gcp"}}],
            annotations={"injected": "true"})]
        p = apply_pod_defaults(pod({"inject": "yes"}), pds)
        c = p["spec"]["containers"][0]
        assert c["env"][0]["name"] == "GOOGLE_APPLICATION_CREDENTIALS"
        assert c["volumeMounts"][0]["mountPath"] == "/secret"
        assert p["spec"]["volumes"][0]["secret"]["secretName"] == "gcp"
        assert p["metadata"]["annotations"]["injected"] == "true"
        assert "poddefault.admission.kubeflow.org/poddefault-gcp-creds" in \
            p["metadata"]["annotations"]

    def test_existing_env_wins(self):
        pds = [pod_default("d", {"x": "y"},
                           env=[{"name": "A", "value": "injected"}])]
        base = pod({"x": "y"})
        base["spec"]["containers"][0]["env"] = [
            {"name": "A", "value": "original"}]
        p = apply_pod_defaults(base, pds)
        assert p["spec"]["containers"][0]["env"] == [
            {"name": "A", "value": "original"}]

    def test_conflicting_defaults_raise(self):
        pds = [pod_default("a", {"x": "y"},
                           env=[{"name": "A", "value": "1"}]),
               pod_default("b", {"x": "y"},
                           env=[{"name": "A", "value": "2"}])]
        with pytest.raises(PodDefaultConflict, match="env A"):
            apply_pod_defaults(pod({"x": "y"}), pds)

    def test_empty_selector_matches_everything(self):
        # k8s LabelSelector convention: {} selects all pods in the namespace
        pds = [pod_default("global", {})]
        assert select_pod_defaults(pod({}), pds) == pds
        assert select_pod_defaults(pod({"any": "label"}), pds) == pds

    def test_admission_hook_mutates_on_create(self):
        cluster = FakeCluster()
        cluster.admission_hooks.append(PodDefaultsWebhook(cluster))
        cluster.create(pod_default(
            "tpu-env", {"needs-tpu-env": "true"},
            env=[{"name": "TPU_RUNTIME", "value": "pjrt"}]))
        created = cluster.create(pod({"needs-tpu-env": "true"}))
        env_vars = {e["name"]: e["value"]
                    for e in created["spec"]["containers"][0]["env"]}
        assert env_vars["TPU_RUNTIME"] == "pjrt"
        # non-matching pod untouched
        other = cluster.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "q", "namespace": "alice"},
            "spec": {"containers": [{"name": "m", "image": "i"}]}})
        assert "env" not in other["spec"]["containers"][0]


class TestBuildManager:
    def test_full_control_plane_assembles_and_converges(self):
        from kubeflow_tpu.controllers import build_manager
        cluster = FakeCluster()
        cluster.add_node("cpu-0", {"cpu": 96, "memory": 2 ** 36})
        mgr = build_manager(cluster)
        assert len(mgr.controllers) >= 10
        assert len(cluster.admission_hooks) == 1
        cluster.create(notebook_manifest())
        mgr.run_pending()
        cluster.tick()
        mgr.run_pending()
        nb = cluster.get("kubeflow.org/v1alpha1", "Notebook", "alice", "nb")
        assert k8s.condition_true(nb, "Ready")
        # QUIESCENCE: with no external changes, a further drain must do
        # zero reconciles — an apply/status write that always bumps
        # resourceVersion would re-enqueue owners forever (hot loop under
        # start_all) and this is the regression guard for that
        assert sum(c.run_pending() for c in mgr.controllers) == 0


class TestGatekeeper:
    def test_session_lifecycle_and_expiry(self):
        now = [0.0]
        store = SessionStore(ttl_s=100, clock=lambda: now[0])
        token = store.create()
        assert store.valid(token)
        now[0] = 101.0
        assert not store.valid(token)
        assert not store.valid("bogus")

    def test_no_password_fails_closed(self):
        import base64
        gate = Gatekeeper(username="admin", password="")
        assert not gate.check_credentials("admin", "")
        header = "Basic " + base64.b64encode(b"admin:").decode()
        assert not gate.check_basic_header(header)
        assert gate.login("admin", "") is None

    def test_credential_check(self):
        gate = Gatekeeper(username="admin", password="s3cret")
        assert gate.check_credentials("admin", "s3cret")
        assert not gate.check_credentials("admin", "wrong")
        assert not gate.check_credentials("root", "s3cret")
        import base64
        header = "Basic " + base64.b64encode(b"admin:s3cret").decode()
        assert gate.check_basic_header(header)
        assert not gate.check_basic_header("Basic garbage!!")

    def test_http_login_auth_logout_flow(self):
        server = GatekeeperServer(Gatekeeper(username="u", password="p"))
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            # unauthorized before login
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{base}/auth")
            assert e.value.code == 401
            # login → cookie
            req = urllib.request.Request(
                f"{base}/login", data=b"username=u&password=p",
                headers={"Content-Type":
                         "application/x-www-form-urlencoded"})
            with urllib.request.urlopen(req) as resp:
                cookie = resp.headers["Set-Cookie"].split(";")[0]
            # authorized with cookie
            req = urllib.request.Request(f"{base}/auth",
                                         headers={"Cookie": cookie})
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
            # logout revokes
            req = urllib.request.Request(f"{base}/logout",
                                         headers={"Cookie": cookie})
            urllib.request.urlopen(req)
            req = urllib.request.Request(f"{base}/auth",
                                         headers={"Cookie": cookie})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req)
            assert e.value.code == 401
        finally:
            server.stop()

    def test_login_page_served(self):
        server = GatekeeperServer(Gatekeeper(username="u", password="p"))
        port = server.start()
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as r:
                assert r.headers["Content-Type"].startswith("text/html")
                assert b'action="/login"' in r.read()
        finally:
            server.stop()

    def test_bad_login_rejected(self):
        server = GatekeeperServer(Gatekeeper(username="u", password="p"))
        port = server.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/login",
                data=b"username=u&password=nope")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req)
            assert e.value.code == 401
        finally:
            server.stop()

    def test_login_redirects_back_with_rd(self):
        """kflogin browser flow: rd param rides the form, success 303s
        back to the original destination, failure 303s to the error page."""
        server = GatekeeperServer(Gatekeeper(username="u", password="p"))
        port = server.start()
        base = f"http://127.0.0.1:{port}"

        class NoRedirect(urllib.request.HTTPErrorProcessor):
            def http_response(self, request, response):
                return response
        opener = urllib.request.build_opener(NoRedirect)
        try:
            # the login page embeds the rd and shows the error banner
            with opener.open(f"{base}/login?rd=%2Fnotebooks&error=1") as r:
                page = r.read().decode()
            assert 'value="/notebooks"' in page
            assert "Invalid username or password" in page
            # good credentials: 303 to rd with the session cookie
            req = urllib.request.Request(
                f"{base}/login", data=b"username=u&password=p&rd=%2Fapp")
            with opener.open(req) as resp:
                assert resp.status == 303
                assert resp.headers["Location"] == "/app"
                assert "kubeflow-session" in resp.headers["Set-Cookie"]
            # bad credentials: 303 back to the form with error flag
            req = urllib.request.Request(
                f"{base}/login", data=b"username=u&password=no&rd=%2Fapp")
            with opener.open(req) as resp:
                assert resp.status == 303
                assert resp.headers["Location"] == "/login?error=1&rd=%2Fapp"
        finally:
            server.stop()

    def test_open_redirect_clamped(self):
        from kubeflow_tpu.webapps.gatekeeper import safe_redirect
        assert safe_redirect("/ok/path") == "/ok/path"
        assert safe_redirect("//evil.com/x") == "/"
        assert safe_redirect("http://evil.com") == "/"
        assert safe_redirect(None) == "/"
        assert safe_redirect("relative") == "/"
        # browsers fold \ into / — '/\evil.com' would become //evil.com
        assert safe_redirect("/\\evil.com") == "/"
        assert safe_redirect("/a\\b") == "/"
        # CR/LF would splice raw headers into the 303 (response splitting)
        assert safe_redirect("/a\r\nSet-Cookie: evil=1") == "/"
        assert safe_redirect("/a%0d%0ax") == "/a%0d%0ax"  # encoded is inert


class TestAccessManagement:
    """KFAM Binding grant API (SURVEY §2.6 access-management swagger):
    Profile + Binding over HTTP against the live cluster."""

    @pytest.fixture
    def kfam(self):
        from kubeflow_tpu.cluster import FakeCluster
        from kubeflow_tpu.controllers.profile import ProfileReconciler
        from kubeflow_tpu.controllers.runtime import Manager
        from kubeflow_tpu.webapps.access_management import \
            AccessManagementServer
        cluster = FakeCluster(auto_schedule=False, auto_run=False)
        mgr = Manager(cluster)
        mgr.add(ProfileReconciler())
        server = AccessManagementServer(cluster)
        server.start()
        yield cluster, mgr, server
        server.stop()
        for c in mgr.controllers:
            c.stop()

    def _req(self, server, method, path, payload=None):
        import json as _json
        data = _json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}", data=data,
            method=method, headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, _json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, _json.loads(e.read())

    def test_profile_then_binding_grant_flow(self, kfam):
        cluster, mgr, server = kfam
        code, _ = self._req(server, "POST", "/kfam/v1/profiles",
                            {"name": "team-a",
                             "owner": {"name": "alice@corp.io"}})
        assert code == 200
        for _ in range(3):
            mgr.run_pending()
        code, body = self._req(server, "GET", "/kfam/v1/profiles")
        assert body["profiles"][0]["ready"] is True

        # grant bob edit in team-a
        binding = {"user": {"kind": "User", "name": "bob@corp.io"},
                   "referredNamespace": "team-a",
                   "roleRef": {"kind": "ClusterRole",
                               "name": "kubeflow-edit"}}
        code, _ = self._req(server, "POST", "/kfam/v1/bindings", binding)
        assert code == 200
        rbs = cluster.list("rbac.authorization.k8s.io/v1", "RoleBinding",
                           "team-a")
        granted = [rb for rb in rbs
                   if rb["metadata"].get("labels", {}).get("user")]
        assert granted[0]["roleRef"]["name"] == "kubeflow-edit"
        assert granted[0]["subjects"][0]["name"] == "bob@corp.io"

        # listable + filterable
        code, body = self._req(
            server, "GET",
            "/kfam/v1/bindings?namespace=team-a&user=bob@corp.io")
        assert len(body["bindings"]) == 1
        code, body = self._req(
            server, "GET", "/kfam/v1/bindings?role=kubeflow-admin")
        assert body["bindings"] == []

        # revoke
        code, _ = self._req(server, "DELETE", "/kfam/v1/bindings", binding)
        assert code == 200
        code, body = self._req(server, "GET",
                               "/kfam/v1/bindings?namespace=team-a")
        assert body["bindings"] == []

    def test_binding_validation(self, kfam):
        _, _, server = kfam
        code, body = self._req(server, "POST", "/kfam/v1/bindings",
                               {"user": {"name": "x"},
                                "referredNamespace": "ns",
                                "roleRef": {"name": "cluster-admin"}})
        assert code == 400
        assert "roleRef" in body["error"]
        code, _ = self._req(server, "POST", "/kfam/v1/bindings",
                            {"referredNamespace": "ns"})
        assert code == 400
