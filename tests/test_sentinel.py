"""Numeric-integrity sentinel tests (ISSUE 17).

Tiered like the health/chaos suites:
- pure-core: the detector bank (NaN/Inf, rolling z-score arming rules,
  cross-replica agreement naming the replica), the evidence wire
  format, the replay-range contract, the chaos fault hook — no cluster,
  no jax compute;
- checkpoint: LKG tagging monotonicity, retention that counts only
  INTACT steps and never evicts the LKG, rollback discard, and the
  max_step-capped restore walk — tiny raw numpy pytrees;
- control-plane: the operator's anomaly rollback over FakeCluster
  (directive annotation, budget exhaustion, replay arming on the
  second same-LKG trip, suspect host blame, the rendered worker env)
  plus the heartbeat numeric canary;
- ledger: the rollback_recompute split in obs/goodput.py decompose;
- soak (slow): the worker-level trip drill; the full SentinelSoak
  scenarios ride tests/test_chaos.py and bench.py --mode sentinel.
"""

import dataclasses
import json
import math
import time

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.api.trainingjob import (ANOMALY_ANNOTATION,
                                          ANOMALY_COUNT_ANNOTATION,
                                          ANOMALY_ROLLBACK_ANNOTATION,
                                          HEARTBEAT_ANNOTATION,
                                          SUSPECT_ANNOTATION)
from kubeflow_tpu.cluster.fake import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import (RESTART_COUNT_ANNOTATION,
                                             TrainingJobReconciler)
from kubeflow_tpu.runtime import sentinel as S
from kubeflow_tpu.scheduler import health as H
from kubeflow_tpu.scheduler.core import SliceScheduler
from kubeflow_tpu.scheduler.queue import SchedulerConfig

pytestmark = pytest.mark.sentinel


# ------------------------------------------------------------ detectors


class TestDetectors:
    def test_nan_loss_trips_immediately(self):
        s = S.NumericSentinel()
        ev = s.observe(3, loss=float("nan"), lkg=2)
        assert ev is not None and ev.kind == S.KIND_NAN_LOSS
        assert ev.step == 3 and ev.lkg == 2 and math.isnan(ev.value)

    def test_inf_grad_trips_before_loss(self):
        s = S.NumericSentinel()
        ev = s.observe(1, loss=1.0, grad_norm=float("inf"))
        assert ev is not None and ev.kind == S.KIND_NAN_GRAD
        assert math.isinf(ev.value)

    def test_spike_arms_only_after_window_fills(self):
        # the first window_steps samples SET the baseline: a huge value
        # inside the warmup must not trip (a fresh model's loss cliff)
        s = S.NumericSentinel(spike_z=3.0, window_steps=4)
        assert s.observe(1, loss=1.0) is None
        assert s.observe(2, loss=50.0) is None     # warmup: no trip
        s2 = S.NumericSentinel(spike_z=3.0, window_steps=4)
        for step, loss in enumerate((1.0, 1.1, 0.9, 1.05), start=1):
            assert s2.observe(step, loss=loss) is None
        ev = s2.observe(5, loss=50.0, lkg=4)       # armed: trips
        assert ev is not None and ev.kind == S.KIND_LOSS_SPIKE
        assert ev.lkg == 4 and ev.detail["z"] > 3.0

    def test_descending_loss_never_trips(self):
        # a healthy converging curve reads as NEGATIVE z: zero
        # false-positive budget on the happy path
        s = S.NumericSentinel(spike_z=2.0, window_steps=8)
        for step in range(1, 41):
            loss = 10.0 / (1.0 + 0.1 * step)
            assert s.observe(step, loss=loss) is None, step

    def test_tripping_sample_never_launders_the_baseline(self):
        # stats update only on ACCEPTED samples: the same spike value
        # must trip again on the next window, not absorb into the mean
        s = S.NumericSentinel(spike_z=3.0, window_steps=4)
        for step, loss in enumerate((1.0, 1.1, 0.9, 1.05), start=1):
            s.observe(step, loss=loss)
        assert s.observe(5, loss=50.0) is not None
        assert s.observe(6, loss=50.0) is not None
        assert s.trips == 2

    def test_replica_skew_names_the_replica(self):
        s = S.NumericSentinel()
        ev = s.observe(7, replica_sqnorms=[1.0, 1.0, 1.002, 1.0], lkg=4)
        assert ev is not None and ev.kind == S.KIND_REPLICA_SKEW
        assert ev.detail["replica"] == 2 and ev.lkg == 4
        # a NaN replica is named too (the comparison can't rank it)
        ev = S.NumericSentinel().observe(
            7, replica_sqnorms=[1.0, float("nan")])
        assert ev is not None and ev.detail["replica"] == 1

    def test_agreement_tolerance_absorbs_reduce_order(self):
        s = S.NumericSentinel()
        # sub-rtol jitter (nondeterministic reduce order) and a single
        # replica (nothing to compare) both stay silent
        assert s.observe(1, replica_sqnorms=[1.0, 1.0 + 1e-7]) is None
        assert s.observe(2, replica_sqnorms=[1.0]) is None

    def test_parse_replay_range(self):
        assert S.parse_replay_range("4:6") == (4, 6)
        for bad in (None, "", "garbage", "6:4", "4:4", "-1:2", "a:b"):
            assert S.parse_replay_range(bad) is None, bad

    def test_evidence_wire_round_trip_carries_nan(self):
        ev = S.AnomalyEvidence(kind=S.KIND_NAN_LOSS, step=12,
                               value=float("nan"), lkg=8,
                               detail={"z": 9.1})
        raw = ev.to_json()
        json.loads(raw)                      # strict-JSON parseable
        back = S.AnomalyEvidence.from_json(raw)
        assert back is not None and math.isnan(back.value)
        assert (back.kind, back.step, back.lkg) == (ev.kind, 12, 8)
        assert back.detail == {"z": 9.1}

    def test_evidence_from_json_degrades_on_garbage(self):
        # a malformed annotation must read as "no evidence", never
        # crash the operator's reconcile loop
        for raw in ("not json", "{}", json.dumps({"kind": "x"}),
                    json.dumps({"step": "NaN", "kind": "x"})):
            assert S.AnomalyEvidence.from_json(raw) is None, raw

    def test_sentinel_rejects_degenerate_config(self):
        with pytest.raises(ValueError, match="spike_z"):
            S.NumericSentinel(spike_z=0)
        with pytest.raises(ValueError, match="window_steps"):
            S.NumericSentinel(window_steps=1)


# ------------------------------------------------------ chaos fault hook


class TestNumericFaultHook:
    def test_from_env_contract(self, tmp_path):
        assert S.NumericFaultHook.from_env(env={}) is None
        with pytest.raises(ValueError, match="kind:step"):
            S.NumericFaultHook.from_env(env={S.NUMERIC_FAULT_ENV: "nan"})
        hook = S.NumericFaultHook.from_env(env={
            S.NUMERIC_FAULT_ENV: "spike:7:16.0",
            S.NUMERIC_FAULT_MARK_ENV: str(tmp_path / "mark"),
            S.NUMERIC_FAULT_FIRES_ENV: "2"})
        assert (hook.kind, hook.at_step, hook.scale,
                hook.max_fires) == ("spike", 7, 16.0, 2)
        with pytest.raises(ValueError, match="unknown numeric fault"):
            S.NumericFaultHook("rowhammer", 1, 1.0, None)

    def test_fire_budget_persists_across_processes(self, tmp_path):
        # the mark file is the whole point: a rollback-restarted segment
        # must not re-poison itself forever
        mark = str(tmp_path / "mark")
        hook = S.NumericFaultHook("nan", 5, float("nan"), mark,
                                  max_fires=2)
        assert not hook.should_fire(4)
        assert hook.should_fire(5)
        hook._record_fire()
        assert hook.should_fire(5)           # 1 < max_fires=2
        hook._record_fire()
        assert not hook.should_fire(5)       # budget spent
        fresh = S.NumericFaultHook("nan", 5, float("nan"), mark,
                                   max_fires=2)
        assert not fresh.should_fire(5)      # ...and it persisted

    @pytest.mark.compute
    def test_poison_corrupts_params_at_armed_step_only(self, tmp_path):
        import jax.numpy as jnp

        @dataclasses.dataclass
        class _State:
            params: dict

        state = _State(params={"w": jnp.ones((4,))})
        hook = S.NumericFaultHook("nan", 3, float("nan"),
                                  str(tmp_path / "mark"))
        assert hook.poison(state, 2) is state          # not armed
        out = hook.poison(state, 3)
        assert bool(jnp.isnan(out.params["w"]).all())
        assert hook.poison(state, 3) is state          # budget spent
        spiked = S.NumericFaultHook("spike", 1, 8.0, None).poison(
            _State(params={"w": jnp.ones((4,))}), 1)
        assert float(spiked.params["w"][0]) == pytest.approx(8.0)


# ------------------------------------------------- checkpoint LKG tier


class TestCheckpointLKG:
    def _mgr(self, directory, steps=(1, 2, 3), max_to_keep=3):
        import numpy as np
        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        m = CheckpointManager(str(directory), max_to_keep=max_to_keep,
                              save_interval_steps=1,
                              retry_backoff_s=0.01)
        for step in steps:
            m.save(step, {"params": {"w": np.full((64,), float(step))}},
                   force=True)
        m.wait()
        return m, np

    def test_lkg_tag_is_monotonic_and_outlives_the_manager(self, tmp_path):
        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        m, _ = self._mgr(tmp_path, steps=(1, 2))
        try:
            assert m.lkg_step() is None
            m.tag_lkg(1)
            assert m.lkg_step() == 1
            m.tag_lkg(2)
            m.tag_lkg(1)                    # stale tag never regresses
            assert m.lkg_step() == 2
        finally:
            m.close()
        m2 = CheckpointManager(str(tmp_path))
        try:
            assert m2.lkg_step() == 2       # a restarted worker reads it
        finally:
            m2.close()

    def test_retention_never_evicts_the_lkg(self, tmp_path):
        import numpy as np
        m, _ = self._mgr(tmp_path, steps=(1,), max_to_keep=2)
        try:
            m.tag_lkg(1)
            for step in (2, 3, 4, 5):
                m.save(step, {"params": {"w": np.full((64,),
                                                      float(step))}},
                       force=True)
            m.wait()
            # keep-last-2 newest + the LKG, which costs no slot
            assert m.all_steps() == [1, 4, 5]
            ok, reason = m.verify_step(1)
            assert ok, reason
        finally:
            m.close()

    def test_truncated_newest_cannot_evict_the_last_restorable(
            self, tmp_path):
        # satellite (b): retention counts only INTACT committed steps —
        # with keep-last-1, a truncated newest must not let the prior
        # (only restorable) step be GC'd, and restore falls back to it
        import numpy as np
        from kubeflow_tpu.cluster.chaos import truncate_checkpoint_payload
        m, _ = self._mgr(tmp_path, steps=(1,), max_to_keep=1)
        try:
            m.tag_lkg(1)
            m.save(2, {"params": {"w": np.full((64,), 2.0)}}, force=True)
            m.wait()
            truncate_checkpoint_payload(str(tmp_path / "2"))
            assert m.latest_step() == 1
            assert m.restore_params()["w"][0] == 1.0
            # a later save retains over the corrupt step without
            # touching it (it may be an in-flight writer) or the LKG
            m.save(3, {"params": {"w": np.full((64,), 3.0)}}, force=True)
            m.wait()
            assert m.all_steps() == [1, 2, 3]
            assert m.latest_step() == 3
        finally:
            m.close()

    def test_discard_steps_after_clears_tainted_remains(self, tmp_path):
        m, _ = self._mgr(tmp_path, steps=(1, 2, 3))
        try:
            m.discard_steps_after(1)
            assert m.all_steps() == [1]
            assert m.restore_params()["w"][0] == 1.0
        finally:
            m.close()

    def test_restore_walk_capped_at_lkg_falls_back_past_corrupt(
            self, tmp_path):
        # the anomaly-rollback restore: newest intact step <= LKG, and
        # if the capped step itself is corrupt the walk keeps falling
        from kubeflow_tpu.cluster.chaos import truncate_checkpoint_payload
        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        m, _ = self._mgr(tmp_path, steps=(1, 2, 3))
        try:
            assert m._restore_with_fallback(lambda s: s, None,
                                            max_step=2) == 2
        finally:
            m.close()
        truncate_checkpoint_payload(str(tmp_path / "2"))
        # the rollback restore runs in the RESTARTED worker: a fresh
        # manager (fresh verify cache) must reject the corrupt LKG and
        # keep walking down
        m2 = CheckpointManager(str(tmp_path))
        try:
            assert m2._restore_with_fallback(lambda s: s, None,
                                             max_step=2) == 1
        finally:
            m2.close()


# ------------------------------------------------------- control plane


def tpujob(name="job", ckpt="/ckpt/job", max_rollbacks=None,
           integrity=None):
    spec = {
        "replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [
                {"name": "jax", "image": "trainer:v1"}]}}}},
        "schedulingPolicy": {"queue": "research", "priority": 0,
                             "preemptible": False},
        "checkpointDir": ckpt,
    }
    rp = {"backoffLimit": 6, "restartBackoffSeconds": 0}
    if max_rollbacks is not None:
        rp["maxAnomalyRollbacks"] = max_rollbacks
    spec["runPolicy"] = rp
    if integrity is not None:
        spec["integrity"] = integrity
    return {"apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": "kubeflow"},
            "spec": spec}


def sched_env():
    # two pools: a second trip's folded evidence (2 x weight 2.0) can
    # quarantine the suspect host, and the gang must still have
    # somewhere to rebind
    cluster = FakeCluster()
    cluster.add_tpu_slice_nodes("v5e-8", pool="pool-a")
    cluster.add_tpu_slice_nodes("v5e-8", pool="pool-b")
    mgr = Manager(cluster)
    mgr.add(SliceScheduler(SchedulerConfig()))
    mgr.add(TrainingJobReconciler("TPUJob"))
    return cluster, mgr


def drive(cluster, mgr, ticks=4):
    for _ in range(ticks):
        mgr.run_pending()
        cluster.tick()
    mgr.run_pending()


def get_job(cluster, name="job"):
    return cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                       name)


def pod_env(cluster, name):
    pod = cluster.get("v1", "Pod", "kubeflow", name)
    return {e["name"]: e.get("value")
            for e in pod["spec"]["containers"][0].get("env", [])}


def trip(cluster, victim="job-worker-0-1", step=12, lkg=8,
         kind=S.KIND_NAN_LOSS):
    """Play the worker's part of the contract: post evidence on our own
    pod, then die with the anomaly exit (Failed phase)."""
    ev = S.AnomalyEvidence(kind=kind, step=step, value=float("nan"),
                           lkg=lkg)
    cluster.patch("v1", "Pod", "kubeflow", victim,
                  {"metadata": {"annotations": {
                      ANOMALY_ANNOTATION: ev.to_json()}}})
    cluster.fail_pod("kubeflow", victim, "sentinel trip (exit 76)")


def stop(mgr):
    for c in mgr.controllers:
        c.stop()


class TestOperatorRollback:
    def test_trip_writes_rollback_directive_and_blames_host(self):
        cluster, mgr = sched_env()
        cluster.create(tpujob())
        drive(cluster, mgr)
        victim = "job-worker-0-1"
        node = cluster.get("v1", "Pod", "kubeflow",
                           victim)["spec"]["nodeName"]
        restarts_before = k8s.annotations_of(get_job(cluster)).get(
            RESTART_COUNT_ANNOTATION)
        trip(cluster, victim)
        op = TrainingJobReconciler("TPUJob")
        op.reconcile(cluster, ("kubeflow", "job"))
        job = get_job(cluster)
        anns = k8s.annotations_of(job)
        assert anns[ANOMALY_COUNT_ANNOTATION] == "1"
        d = json.loads(anns[ANOMALY_ROLLBACK_ANNOTATION])
        assert d == {"lkgStep": 8, "tripStep": 12,
                     "kind": S.KIND_NAN_LOSS, "count": 1}
        # rolled back to the LKG via resumeFrom + the directive (NOT a
        # crash: the gang-restart count is untouched — the budget that
        # moved is the anomaly one)
        assert job["spec"]["resumeFrom"] == "/ckpt/job"
        assert anns.get(RESTART_COUNT_ANNOTATION) == restarts_before
        # the evidence pod's host carries the blame
        assert anns[SUSPECT_ANNOTATION] == node
        rec = H.health_of(cluster.get("v1", "Node", "", node))
        assert rec["last"] == H.EVENT_NUMERIC_ANOMALY
        cond = k8s.get_condition(job, "Restarting")
        assert cond["reason"] == "NumericAnomaly"
        assert "LKG step 8" in cond["message"]
        stop(mgr)

    def test_second_trip_same_lkg_arms_replay_and_renders_env(self):
        cluster, mgr = sched_env()
        cluster.create(tpujob())
        drive(cluster, mgr)
        trip(cluster)
        drive(cluster, mgr, ticks=6)
        # the recreated gang resumes pinned to the LKG, no replay yet
        env = pod_env(cluster, "job-worker-0-0")
        assert env.get(S.RESUME_STEP_ENV) == "8"
        assert S.REPLAY_RANGE_ENV not in env
        # second trip over the SAME lkg: the fault reproduces — arm the
        # deterministic replay of the suspect range
        trip(cluster)
        op = TrainingJobReconciler("TPUJob")
        op.reconcile(cluster, ("kubeflow", "job"))
        d = json.loads(k8s.annotations_of(get_job(cluster))[
            ANOMALY_ROLLBACK_ANNOTATION])
        assert d["count"] == 2 and d["replay"] == "8:12"
        drive(cluster, mgr, ticks=6)
        env = pod_env(cluster, "job-worker-0-0")
        assert env.get(S.RESUME_STEP_ENV) == "8"
        assert env.get(S.REPLAY_RANGE_ENV) == "8:12"
        stop(mgr)

    def test_integrity_spec_rendered_into_worker_env(self):
        cluster, mgr = sched_env()
        cluster.create(tpujob(integrity={
            "enabled": True, "spikeZ": 6.0, "windowSteps": 16,
            "checkEverySteps": 5}))
        drive(cluster, mgr)
        env = pod_env(cluster, "job-worker-0-0")
        assert env.get("KFTPU_INTEGRITY") == "1"
        assert env.get("KFTPU_INTEGRITY_SPIKE_Z") == "6.0"
        assert env.get("KFTPU_INTEGRITY_WINDOW") == "16"
        assert env.get("KFTPU_INTEGRITY_CHECK_EVERY") == "5"
        stop(mgr)

    def test_budget_exhaustion_fails_the_job_with_evidence(self):
        cluster, mgr = sched_env()
        cluster.create(tpujob(max_rollbacks=1))
        drive(cluster, mgr)
        trip(cluster)
        op = TrainingJobReconciler("TPUJob")
        op.reconcile(cluster, ("kubeflow", "job"))
        drive(cluster, mgr, ticks=6)
        trip(cluster)
        op.reconcile(cluster, ("kubeflow", "job"))
        job = get_job(cluster)
        cond = k8s.get_condition(job, "Failed")
        assert cond is not None and cond["status"] == "True"
        assert cond["reason"] == "AnomalyBudgetExceeded"
        assert "nan-loss at step 12" in cond["message"]
        # the budget, not the count, is what stopped it
        assert k8s.annotations_of(job)[ANOMALY_COUNT_ANNOTATION] == "1"
        stop(mgr)

    def test_directive_cleared_once_chief_passes_the_trip(self):
        cluster, mgr = sched_env()
        cluster.create(tpujob())
        drive(cluster, mgr)
        trip(cluster)
        op = TrainingJobReconciler("TPUJob")
        op.reconcile(cluster, ("kubeflow", "job"))
        drive(cluster, mgr, ticks=6)

        def beat(step):
            cluster.patch(
                "v1", "Pod", "kubeflow", "job-worker-0-0",
                {"metadata": {"annotations": {HEARTBEAT_ANNOTATION:
                    json.dumps({"step": step, "time": time.time()})}}})

        # still replaying the suspect range: the directive stays
        beat(10)
        op.reconcile(cluster, ("kubeflow", "job"))
        anns = k8s.annotations_of(get_job(cluster))
        assert ANOMALY_ROLLBACK_ANNOTATION in anns
        # past the trip step: the range re-ran clean — consume it so
        # future restarts resume from the NEWEST checkpoint again
        beat(13)
        op.reconcile(cluster, ("kubeflow", "job"))
        anns = k8s.annotations_of(get_job(cluster))
        assert not anns.get(ANOMALY_ROLLBACK_ANNOTATION)
        # ...but the consumed-rollback count survives for the budget
        assert anns[ANOMALY_COUNT_ANNOTATION] == "1"
        stop(mgr)

    def test_malformed_evidence_degrades_to_crash_restart(self):
        cluster, mgr = sched_env()
        cluster.create(tpujob())
        drive(cluster, mgr)
        cluster.patch("v1", "Pod", "kubeflow", "job-worker-0-1",
                      {"metadata": {"annotations": {
                          ANOMALY_ANNOTATION: "not json"}}})
        cluster.fail_pod("kubeflow", "job-worker-0-1", "crash")
        op = TrainingJobReconciler("TPUJob")
        op.reconcile(cluster, ("kubeflow", "job"))
        anns = k8s.annotations_of(get_job(cluster))
        # no rollback directive, no anomaly budget spend — the ordinary
        # gang-restart path (which DOES count) handled it
        assert ANOMALY_ROLLBACK_ANNOTATION not in anns
        assert ANOMALY_COUNT_ANNOTATION not in anns
        assert anns.get(RESTART_COUNT_ANNOTATION) == "1"
        stop(mgr)


class TestHeartbeatCanary:
    def _running(self):
        cluster, mgr = sched_env()
        cluster.create(tpujob())
        drive(cluster, mgr)
        return cluster, mgr

    def _beat(self, cluster, pod, step, t=None, **extra):
        body = {"step": step, "time": time.time() if t is None else t}
        body.update(extra)
        cluster.patch("v1", "Pod", "kubeflow", pod,
                      {"metadata": {"annotations": {
                          HEARTBEAT_ANNOTATION: json.dumps(body)}}})

    def test_nan_heartbeat_flags_host_even_without_sentinel(self):
        # satellite (a): lastLoss rides the liveness beat, so the
        # operator flags a NaN-emitting worker with spec.integrity OFF
        cluster, mgr = self._running()
        node = cluster.get("v1", "Pod", "kubeflow",
                           "job-worker-0-0")["spec"]["nodeName"]
        op = TrainingJobReconciler("TPUJob")
        self._beat(cluster, "job-worker-0-0", 7, lastLoss="nan")
        op.reconcile(cluster, ("kubeflow", "job"))
        rec = H.health_of(cluster.get("v1", "Node", "", node))
        assert rec["events"] == 1
        assert rec["last"] == H.EVENT_NUMERIC_ANOMALY
        # same beat re-observed: deduped, no double-charge
        op.reconcile(cluster, ("kubeflow", "job"))
        rec = H.health_of(cluster.get("v1", "Node", "", node))
        assert rec["events"] == 1
        # a NEW step still reporting garbage is new evidence
        self._beat(cluster, "job-worker-0-0", 8, lastGradNorm="inf")
        op.reconcile(cluster, ("kubeflow", "job"))
        rec = H.health_of(cluster.get("v1", "Node", "", node))
        assert rec["events"] == 2
        stop(mgr)

    def test_stale_or_finite_beats_never_flag(self):
        cluster, mgr = self._running()
        op = TrainingJobReconciler("TPUJob")
        node1 = cluster.get("v1", "Pod", "kubeflow",
                            "job-worker-0-1")["spec"]["nodeName"]
        # a stale NaN beat is not evidence (the worker may be long gone)
        self._beat(cluster, "job-worker-0-1", 5,
                   t=time.time() - 10_000, lastLoss="nan")
        # a fresh FINITE beat is the healthy path
        self._beat(cluster, "job-worker-0-0", 5, lastLoss="2.25",
                   lastGradNorm="0.5")
        op.reconcile(cluster, ("kubeflow", "job"))
        rec = H.health_of(cluster.get("v1", "Node", "", node1))
        assert rec["events"] == 0
        stop(mgr)


class TestHeartbeatReporterPayload:
    def test_beat_carries_repr_floats_and_annotate_posts_evidence(self):
        from kubeflow_tpu.runtime.metrics import HeartbeatReporter
        cluster = FakeCluster()
        cluster.create(k8s.make("v1", "Pod", "w0", namespace="kubeflow"))
        hr = HeartbeatReporter(cluster, "kubeflow", "w0", interval_s=0)
        assert hr.beat(7, force=True, loss=float("nan"), grad_norm=2.0)
        raw = k8s.annotations_of(cluster.get(
            "v1", "Pod", "kubeflow", "w0"))[HEARTBEAT_ANNOTATION]
        body = json.loads(raw)           # strict JSON: NaN is a string
        assert body["step"] == 7
        assert math.isnan(float(body["lastLoss"]))
        assert float(body["lastGradNorm"]) == 2.0
        ev = S.AnomalyEvidence(S.KIND_NAN_LOSS, 7, float("nan"), lkg=4)
        assert hr.annotate(ANOMALY_ANNOTATION, ev.to_json())
        posted = k8s.annotations_of(cluster.get(
            "v1", "Pod", "kubeflow", "w0"))[ANOMALY_ANNOTATION]
        assert S.AnomalyEvidence.from_json(posted).lkg == 4


# ------------------------------------------------------- goodput ledger


class TestRollbackLedger:
    def _span(self, name, start, end=None, **attrs):
        rec = {"trace_id": "t", "span_id": "s", "parent_id": "",
               "name": name, "component": "test", "start": float(start),
               "end": float(end if end is not None else start)}
        if attrs:
            rec["attrs"] = attrs
        return rec

    def test_replay_after_anomaly_is_rollback_recompute(self):
        from kubeflow_tpu.obs import goodput as gp
        led = gp.decompose([
            self._span("window", 0.0, 6.0, step=6, steps=6),
            self._span(gp.SPAN_ANOMALY, 6.5, step=6, lkg=4),
            # rolled back to 4: steps 5,6 replay, then new ground 7,8
            self._span("window", 10.0, 12.0, step=6, steps=2),
            self._span("window", 12.0, 14.0, step=8, steps=2),
        ])
        assert led["stepsRolledBack"] == 2
        assert led["badputSeconds"][gp.BADPUT_ROLLBACK] == \
            pytest.approx(2.0)
        assert led["badputSeconds"][gp.BADPUT_RECOMPUTE] == \
            pytest.approx(0.0)
        assert led["goodputSeconds"] == pytest.approx(8.0)
        assert gp.categories_sum_ok(led)

    def test_replay_before_anomaly_stays_restart_recompute(self):
        # only windows AFTER the anomaly span are the sentinel's bill —
        # an ordinary crash replay earlier in the stream keeps its
        # restart_recompute attribution
        from kubeflow_tpu.obs import goodput as gp
        led = gp.decompose([
            self._span("window", 0.0, 6.0, step=6, steps=6),
            self._span("window", 8.0, 10.0, step=6, steps=2),
            self._span(gp.SPAN_ANOMALY, 20.0, step=6, lkg=4),
        ])
        assert led["stepsRolledBack"] == 0
        assert led["badputSeconds"][gp.BADPUT_ROLLBACK] == \
            pytest.approx(0.0)
        assert led["badputSeconds"][gp.BADPUT_RECOMPUTE] == \
            pytest.approx(2.0)
        assert gp.categories_sum_ok(led)

    def test_garbage_anomaly_span_ignored(self):
        from kubeflow_tpu.obs import goodput as gp
        led = gp.decompose([
            self._span("window", 0.0, 4.0, step=4, steps=4),
            self._span(gp.SPAN_ANOMALY, 4.5),            # no attrs
            self._span(gp.SPAN_ANOMALY, 4.6, step=2, lkg=6),  # inverted
        ])
        assert led["stepsRolledBack"] == 0
        assert gp.categories_sum_ok(led)


# --------------------------------------------------- worker trip (slow)


@pytest.mark.slow
@pytest.mark.compute
class TestWorkerTrip:
    def test_trip_exits_with_evidence_and_untainted_lkg(
            self, tmp_path, monkeypatch):
        """The worker-level acceptance drill: poison after step 5, the
        sentinel trips when the damage surfaces at step 6, the evidence
        names the LKG (step 4 — cleared by the window AFTER it), and no
        tainted checkpoint was committed past it."""
        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        from kubeflow_tpu.runtime.worker import train
        monkeypatch.setenv(S.NUMERIC_FAULT_ENV, "nan:5")
        monkeypatch.setenv(S.NUMERIC_FAULT_MARK_ENV,
                           str(tmp_path / "mark"))
        ckpt = str(tmp_path / "ckpt")
        res = train(workload="transformer", steps=16, global_batch=8,
                    sync_every=1, checkpoint_dir=ckpt,
                    checkpoint_every=2, seed=0, handle_sigterm=False,
                    integrity=True, integrity_check_every=1,
                    integrity_window=4)
        assert res.anomaly is not None
        # NaN params poison loss AND grads; the grad-norm check runs
        # first in the bank, so that's the kind that names the trip
        assert res.anomaly["kind"] in (S.KIND_NAN_GRAD, S.KIND_NAN_LOSS)
        assert res.anomaly["step"] == 6 and res.anomaly["lkg"] == 4
        m = CheckpointManager(ckpt)
        try:
            assert m.lkg_step() == 4
            # the trip aborted BEFORE the step-6 save: nothing newer
            # than the LKG was committed
            assert max(m.all_steps()) <= 4
        finally:
            m.close()
