"""Observability layer (ISSUE 5): registry semantics, trace spans, span
propagation job → env → worker JSONL, and endpoint smoke tests."""

import json
import math
import threading
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.obs.http import ObsServer
from kubeflow_tpu.obs.registry import (Registry, default_registry,
                                       reset_default_registry)
from kubeflow_tpu.obs.trace import (SPAN_PATH_ENV, TRACE_ID_ANNOTATION,
                                    TRACE_ID_ENV, SpanWriter, load_spans,
                                    reconstruct)

pytestmark = pytest.mark.obs


class TestRegistry:
    def test_counter_inc_and_labels(self):
        r = Registry()
        c = r.counter("jobs_total", "jobs", labels=("queue",))
        c.labels(queue="a").inc()
        c.labels(queue="a").inc(2)
        c.labels(queue="b").inc()
        assert c.labels(queue="a").value == 3
        text = r.render()
        assert 'jobs_total{queue="a"} 3' in text
        assert 'jobs_total{queue="b"} 1' in text
        assert "# TYPE jobs_total counter" in text

    def test_counter_rejects_decrease(self):
        c = Registry().counter("c_total", "c")
        with pytest.raises(ValueError, match="increase"):
            c.inc(-1)

    def test_gauge_set_inc_dec(self):
        g = Registry().gauge("depth", "d")
        g.set(5)
        g.inc(2)
        g.dec(3)
        assert g.value == 4

    def test_label_value_escaping(self):
        r = Registry()
        r.counter("esc_total", "e", labels=("v",)).labels(
            v='say "hi"\\\n').inc()
        text = r.render()
        assert r'esc_total{v="say \"hi\"\\\n"} 1' in text

    def test_help_escaping(self):
        r = Registry()
        r.gauge("h", "line1\nline2 \\ slash")
        assert r"# HELP h line1\nline2 \\ slash" in r.render()

    def test_histogram_buckets_cumulative(self):
        r = Registry()
        h = r.histogram("lat_seconds", "l", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        counts = h.bucket_counts()
        assert counts[0.1] == 1
        assert counts[1.0] == 3
        assert counts[10.0] == 4
        assert counts[math.inf] == 5
        text = r.render()
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="+Inf"} 5' in text
        assert "lat_seconds_count 5" in text
        assert "lat_seconds_sum 56.05" in text

    def test_concurrent_increments_are_exact(self):
        c = Registry().counter("conc_total", "c")
        h = Registry().histogram("conc_seconds", "h", buckets=(1.0,))

        def hammer():
            for _ in range(5000):
                c.inc()
                h.observe(0.5)

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 40000
        assert h.bucket_counts()[1.0] == 40000

    def test_reregistration_idempotent_mismatch_raises(self):
        r = Registry()
        a = r.counter("x_total", "x", labels=("l",))
        assert r.counter("x_total", "x", labels=("l",)) is a
        with pytest.raises(ValueError, match="re-registered"):
            r.gauge("x_total", "x", labels=("l",))
        with pytest.raises(ValueError, match="re-registered"):
            r.counter("x_total", "x", labels=("other",))

    def test_unlabeled_series_render_zero_from_registration(self):
        r = Registry()
        r.counter("fresh_total", "never incremented")
        assert "fresh_total 0" in r.render()

    def test_labeled_metrics_require_labels_and_validate_names(self):
        r = Registry()
        fam = r.counter("l_total", "l", labels=("q",))
        with pytest.raises(ValueError, match="labels"):
            fam.inc()
        with pytest.raises(ValueError, match="labels"):
            fam.labels(wrong="x")
        with pytest.raises(ValueError, match="invalid metric name"):
            r.counter("bad-name", "x")

    def test_integer_values_render_without_decimal_point(self):
        r = Registry()
        r.gauge("g", "g").set(3.0)
        assert "\ng 3\n" in "\n" + r.render()

    def test_remove_drops_series(self):
        r = Registry()
        g = r.gauge("phase", "p", labels=("name",))
        g.labels(name="a").set(1)
        g.remove(name="a")
        assert 'phase{name="a"}' not in r.render()

    def test_disabled_registry_is_noop(self):
        r = Registry(enabled=False)
        c = r.counter("x_total", "x", labels=("l",))
        c.labels(l="a").inc()
        c.inc()
        r.histogram("h", "h").observe(1)
        assert r.render() == ""

    def test_default_registry_honors_disable_env(self, monkeypatch):
        monkeypatch.setenv("KFTPU_OBS_DISABLE", "1")
        reset_default_registry()
        try:
            assert default_registry().enabled is False
        finally:
            monkeypatch.delenv("KFTPU_OBS_DISABLE")
            reset_default_registry()


class TestSpans:
    def test_writer_emits_jsonl(self, tmp_path):
        p = str(tmp_path / "spans.jsonl")
        w = SpanWriter(p, "worker", trace_id="t1")
        w.event("queued", queue="research")
        with w.span("window", step=5):
            pass
        w.close()
        records = [json.loads(line)
                   for line in open(p).read().splitlines()]
        assert [r["name"] for r in records] == ["queued", "window"]
        assert all(r["trace_id"] == "t1" for r in records)
        assert all(r["component"] == "worker" for r in records)
        ev, span = records
        assert ev["start"] == ev["end"]          # point event
        assert span["end"] >= span["start"]
        assert span["attrs"] == {"step": 5}
        assert span["span_id"] and span["span_id"] != ev["span_id"]

    def test_span_records_error(self, tmp_path):
        p = str(tmp_path / "s.jsonl")
        w = SpanWriter(p, "worker", trace_id="t")
        with pytest.raises(RuntimeError):
            with w.span("restore"):
                raise RuntimeError("boom")
        w.close()
        rec = json.loads(open(p).read())
        assert "RuntimeError: boom" in rec["attrs"]["error"]

    def test_from_env(self, tmp_path):
        assert SpanWriter.from_env("worker", env={}) is None
        w = SpanWriter.from_env("worker", env={
            SPAN_PATH_ENV: str(tmp_path / "s.jsonl"),
            TRACE_ID_ENV: "abc"})
        assert w is not None and w.trace_id == "abc"
        w.close()

    def test_load_skips_garbage_and_orders(self, tmp_path):
        p = tmp_path / "s.jsonl"
        p.write_text(
            json.dumps({"trace_id": "t", "name": "b", "start": 2.0,
                        "end": 2.5}) + "\n"
            "not json at all\n"
            '{"valid json": "but not a span"}\n' +
            json.dumps({"trace_id": "t", "name": "a", "start": 1.0,
                        "end": 1.5}) + "\n" +
            json.dumps({"trace_id": "other", "name": "z", "start": 0.0,
                        "end": 0.1}) + "\n")
        spans = load_spans(str(p), trace_id="t")
        assert [s["name"] for s in spans] == ["a", "b"]
        t = reconstruct(str(p), "t")
        assert t["names"] == ["a", "b"]
        assert t["wallSeconds"] == pytest.approx(1.5)
        assert reconstruct(str(tmp_path / "missing.jsonl"),
                           "t")["events"] == []


def _pump(mgr, cluster, ticks: int = 3) -> None:
    for _ in range(ticks):
        mgr.run_pending()
        cluster.tick()
    mgr.run_pending()


@pytest.fixture
def sched_cluster(tmp_path, monkeypatch):
    """FakeCluster + the real scheduler and operator, with a span sink
    configured the way a deployment would (env on the control-plane
    process)."""
    from kubeflow_tpu.cluster.fake import FakeCluster
    from kubeflow_tpu.controllers.runtime import Manager
    from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
    from kubeflow_tpu.scheduler.core import SliceScheduler

    sink = str(tmp_path / "spans.jsonl")
    monkeypatch.setenv(SPAN_PATH_ENV, sink)
    cluster = FakeCluster()
    cluster.add_tpu_slice_nodes("v5e-8")
    mgr = Manager(cluster)
    mgr.add(SliceScheduler())
    mgr.add(TrainingJobReconciler("TPUJob"))
    yield cluster, mgr, sink
    for c in mgr.controllers:
        c.stop()


def _tpujob(name: str = "trace-job", scheduled: bool = True) -> dict:
    spec: dict = {"replicaSpecs": {"TPU": {
        "tpuTopology": "v5e-8",
        "template": {"spec": {"containers": [
            {"name": "jax", "image": "trainer:v1"}]}}}}}
    if scheduled:
        spec["schedulingPolicy"] = {"queue": "research", "priority": 1}
    return {"apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": "kubeflow"},
            "spec": spec}


class TestTracePropagation:
    """The span-propagation contract end to end on the real control
    plane: trace id minted → annotation → pod env → worker JSONL →
    reconstructable timeline. (The REAL-training version of this runs
    in bench.py --mode obs through the contended-scheduler soak.)"""

    def test_job_to_env_to_worker_jsonl(self, sched_cluster):
        from kubeflow_tpu.api import k8s

        cluster, mgr, sink = sched_cluster
        cluster.create(_tpujob())
        _pump(mgr, cluster)

        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                          "kubeflow", "trace-job")
        trace_id = k8s.annotations_of(job).get(TRACE_ID_ANNOTATION)
        assert trace_id, "control plane never minted a trace id"

        # the operator rendered the contract into every worker pod
        pod = cluster.get("v1", "Pod", "kubeflow", "trace-job-worker-0-0")
        env = {e["name"]: e.get("value", "")
               for e in pod["spec"]["containers"][0].get("env", [])}
        assert env[TRACE_ID_ENV] == trace_id
        assert env[SPAN_PATH_ENV] == sink

        # the worker end: a SpanWriter built from exactly that env
        # writes windows that stitch onto the job's trace
        w = SpanWriter.from_env("worker", env=env)
        w.event("train-start", start_step=0, steps=4)
        w.emit("window", start=1.0, end=2.0, step=4, steps=4)
        w.close()

        cluster.set_pod_phase("kubeflow", "trace-job-worker-0-0",
                              "Succeeded")
        _pump(mgr, cluster)

        names = reconstruct(sink, trace_id)["names"]
        for phase in ("queued", "bound", "created", "running",
                      "window", "succeeded"):
            assert phase in names, (phase, names)
        # queue → bind → gang-create precede the worker's windows,
        # completion follows them (windows carry fake timestamps 1.0-2.0
        # < wall clock, so assert order on the control-plane spine only)
        assert names.index("queued") < names.index("bound") \
            < names.index("created")
        assert names.index("created") < names.index("succeeded")

    def test_unmanaged_job_still_gets_trace(self, sched_cluster):
        from kubeflow_tpu.api import k8s

        cluster, mgr, sink = sched_cluster
        cluster.create(_tpujob(name="legacy", scheduled=False))
        _pump(mgr, cluster)
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                          "kubeflow", "legacy")
        trace_id = k8s.annotations_of(job).get(TRACE_ID_ANNOTATION)
        assert trace_id
        names = reconstruct(sink, trace_id)["names"]
        assert "created" in names and "running" in names
        assert "queued" not in names   # legacy path never queues

    def test_scheduler_metrics_exported(self, sched_cluster):
        cluster, mgr, sink = sched_cluster
        cluster.create(_tpujob())
        _pump(mgr, cluster)
        text = default_registry().render()
        assert 'kftpu_sched_queue_depth{queue="research"} 0' in text
        assert 'kftpu_sched_bound_gangs{queue="research"} 1' in text
        assert "kftpu_sched_queue_wait_seconds_count" in text
        assert "kftpu_sched_plan_seconds_count" in text
        # the manager loop's generic per-controller accounting
        assert 'kftpu_reconcile_seconds_count{controller="tpujob"}' in text
        # the operator's phase gauge follows the job
        assert 'kftpu_job_phase{namespace="kubeflow",name="trace-job",' \
               'kind="TPUJob",phase="Running"} 1' in text


class TestEndpoints:
    def test_obs_server_serves_registry(self):
        r = Registry()
        r.counter("smoke_total", "s").inc(3)
        server = ObsServer(r, host="127.0.0.1")
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as resp:
                body = resp.read().decode()
                assert resp.headers["Content-Type"].startswith(
                    "text/plain")
            assert "smoke_total 3" in body
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz") as resp:
                assert json.loads(resp.read())["ok"] is True
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope")
        finally:
            server.stop()

    def test_dashboard_timeline_endpoint(self, sched_cluster):
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app

        cluster, mgr, sink = sched_cluster
        cluster.create(_tpujob())
        _pump(mgr, cluster)
        app = build_dashboard_app(cluster)
        status, body = app.dispatch(
            "GET", "/api/obs/jobs/kubeflow/trace-job", None)
        assert status == 200
        assert body["traceId"]
        assert "queued" in [e["name"] for e in body["events"]]
        assert body["phase"] == "Running"
        status, _ = app.dispatch("GET", "/api/obs/jobs/kubeflow/ghost",
                                 None)
        assert status == 404

    def test_dashboard_timeline_without_sink(self, tmp_path, monkeypatch):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app

        monkeypatch.delenv(SPAN_PATH_ENV, raising=False)
        cluster = FakeCluster()
        cluster.create(_tpujob(scheduled=False))
        app = build_dashboard_app(cluster)
        status, body = app.dispatch(
            "GET", "/api/obs/jobs/kubeflow/trace-job", None)
        assert status == 200
        assert body["events"] == [] and "note" in body

    def test_dashboard_metrics_route(self):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        from kubeflow_tpu.webapps._http import RawResponse

        app = build_dashboard_app(FakeCluster())
        status, body = app.dispatch("GET", "/metrics", None)
        assert status == 200 and isinstance(body, RawResponse)

    def test_controller_manager_metrics_flag(self):
        # --metrics-port=0 keeps the manager scrape surface off; the
        # flag itself parses (deployments render --metrics-port=8080)
        from kubeflow_tpu.manifests.training import tpu_scheduler
        dep = next(o for o in tpu_scheduler()
                   if o["kind"] == "Deployment")
        args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--metrics-port=8080" in args
        anns = dep["spec"]["template"]["metadata"]["annotations"]
        assert anns["prometheus.io/scrape"] == "true"
        assert anns["prometheus.io/port"] == "8080"


class TestHeartbeatGauges:
    def test_last_beat_exported(self):
        from kubeflow_tpu.runtime.metrics import HeartbeatReporter

        class FakeClient:
            def patch(self, *a, **k):
                return {}

        hb = HeartbeatReporter(FakeClient(), "ns", "pod", interval_s=0.0)
        assert hb.beat(41, force=True)
        text = default_registry().render()
        assert "kftpu_heartbeat_last_step 41" in text
        assert "kftpu_heartbeat_last_time_seconds" in text

    def test_failed_beat_leaves_gauges(self):
        from kubeflow_tpu.runtime.metrics import HeartbeatReporter

        class DeadClient:
            def patch(self, *a, **k):
                raise OSError("apiserver down")

        hb = HeartbeatReporter(DeadClient(), "ns", "pod", interval_s=0.0)
        before = hb._g_step.value
        assert hb.beat(99, force=True) is False
        # a FAILED patch must not advertise progress
        assert hb._g_step.value == before


class TestSummaryWarmupDegrade:
    """Satellite: summary(warmup=N) with fewer than N+1 windows must
    degrade gracefully — drop what it can, keep at least the final
    window, never an empty slice."""

    def _logger(self, n: int):
        from kubeflow_tpu.runtime.metrics import MetricsLogger
        m = MetricsLogger(batch_size=10, log_every=0)
        for i in range(n):
            # window i covers 2 steps in 0.2s → 0.1 s/step
            m.record_window((i + 1) * 2, 2, 0.2, {"loss": 1.0})
        return m

    def test_short_history_keeps_final_window(self):
        m = self._logger(2)
        s = m.summary(warmup=5)
        assert s["steps"] == 4
        assert s["mean_step_time_s"] == pytest.approx(0.1)
        assert s["examples_per_sec"] == pytest.approx(100.0)

    def test_single_window_history(self):
        s = self._logger(1).summary(warmup=3)
        assert s["mean_step_time_s"] == pytest.approx(0.1)
        assert s["examples_per_sec"] > 0

    def test_empty_history(self):
        s = self._logger(0).summary(warmup=2)
        assert s == {"steps": 0, "examples_per_sec": 0.0,
                     "mean_step_time_s": 0.0}

    def test_negative_warmup_treated_as_zero(self):
        s = self._logger(3).summary(warmup=-1)
        assert s["steps"] == 6
        assert s["mean_step_time_s"] == pytest.approx(0.1)

    def test_normal_warmup_still_skips(self):
        from kubeflow_tpu.runtime.metrics import MetricsLogger
        m = MetricsLogger(batch_size=10, log_every=0)
        m.record_window(2, 2, 2.0, {})     # compile window, 1 s/step
        m.record_window(4, 2, 0.2, {})
        s = m.summary(warmup=1)
        assert s["mean_step_time_s"] == pytest.approx(0.1)
        assert s["first_window_s"] == pytest.approx(2.0)
