"""Control-plane fault tolerance (ISSUE 14): leader election over Lease
objects, conflict-safe read-modify-writes, controller crash points,
orphan reconciliation, and the split-brain fence.

Fast tier (-m ctrl_chaos): lease/elector/fencing semantics, the
update_with_conflict_retry contract, a two-writer interleaving test per
migrated RMW site, the ConflictError contract at the HTTP apiserver
boundary, snapshot counter preservation, error-requeue backoff, and the
seeded controller kill-points. Slow tier: the ControlPlaneSoak with real
training segments (bench.py --mode ctrl-chaos runs the full menu).
"""

import threading
import time

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.api.trainingjob import (BINDING_ANNOTATION,
                                          PREEMPTED_COUNT_ANNOTATION,
                                          RESIZE_HISTORY_ANNOTATION)
from kubeflow_tpu.cluster import lease as L
from kubeflow_tpu.cluster.chaos import (ControllerChaos, ControllerCrash,
                                        RecordingKubeClient,
                                        TransientAPIError)
from kubeflow_tpu.cluster.client import (ConflictError, NotFoundError,
                                         apply_annotations,
                                         update_with_conflict_retry)
from kubeflow_tpu.cluster.fake import FakeCluster
from kubeflow_tpu.controllers.runtime import Controller, Manager
from kubeflow_tpu.controllers.tpujob import (RESTART_COUNT_ANNOTATION,
                                             TrainingJobReconciler)
from kubeflow_tpu.obs import registry as obsreg
from kubeflow_tpu.scheduler.core import SliceScheduler
from kubeflow_tpu.scheduler import health
from kubeflow_tpu.scheduler.queue import SchedulerConfig, binding_of

pytestmark = pytest.mark.ctrl_chaos

TPU_AV = "tpu.kubeflow.org/v1alpha1"


def tpujob_manifest(name="train", scheduled=False, **spec_extra):
    spec = {
        "checkpointDir": f"/ckpt/{name}",
        "replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [
                {"name": "jax", "image": "trainer:v1"}]}}}},
        "runPolicy": {"backoffLimit": 5},
        **spec_extra,
    }
    if scheduled:
        spec["schedulingPolicy"] = {"queue": "research", "priority": 0,
                                    "preemptible": True}
    return {"apiVersion": TPU_AV, "kind": "TPUJob",
            "metadata": {"name": name, "namespace": "kubeflow"},
            "spec": spec}


def drive(cluster, mgr, ticks=3):
    for _ in range(ticks):
        mgr.run_pending()
        cluster.tick()
        due = [t for c in mgr.controllers for (t, _k) in c._delayed]
        wait = min(due, default=0.0) - time.monotonic()
        if 0 < wait <= 1.0:
            time.sleep(wait + 0.005)
    mgr.run_pending()


# ------------------------------------------------------------ the lease


class TestLeaseContract:
    def test_acquire_creates_lease_with_fencing_token_1(self):
        cluster = FakeCluster()
        res = L.try_acquire(cluster, "kubeflow", "op", "a", 15.0, now=100.0)
        assert res.acquired and res.record.transitions == 1
        obj = cluster.get(L.LEASE_API_VERSION, L.LEASE_KIND,
                          "kubeflow", "op")
        assert obj["spec"][L.HOLDER_FIELD] == "a"

    def test_renew_keeps_token_steal_bumps_it(self):
        cluster = FakeCluster()
        L.try_acquire(cluster, "kubeflow", "op", "a", 10.0, now=100.0)
        renewed = L.try_acquire(cluster, "kubeflow", "op", "a", 10.0,
                                now=105.0)
        assert renewed.acquired and renewed.record.transitions == 1
        # not expired: b cannot take it
        held = L.try_acquire(cluster, "kubeflow", "op", "b", 10.0,
                             now=110.0)
        assert not held.acquired and held.reason == "held"
        # expired: b steals, token bumps — the old holder's writes are
        # orderable as stale by anyone comparing tokens
        stolen = L.try_acquire(cluster, "kubeflow", "op", "b", 10.0,
                               now=120.0)
        assert stolen.acquired and stolen.record.transitions == 2

    def test_concurrent_steal_has_exactly_one_winner(self):
        """The race the rv precondition exists for: two standbys see the
        same expired lease; the second update must 409 and lose."""
        cluster = FakeCluster()
        L.try_acquire(cluster, "kubeflow", "op", "dead", 1.0, now=0.0)

        class Racer:
            """Injects competitor b's steal between a's get and update.
            Deliberately NOT a KubeClient subclass: the base class's
            stub methods would shadow __getattr__ delegation."""

            def __init__(self, inner):
                self.inner = inner
                self.armed = True

            def get(self, *a, **kw):
                out = self.inner.get(*a, **kw)
                if self.armed and a[1] == L.LEASE_KIND:
                    self.armed = False
                    assert L.try_acquire(self.inner, "kubeflow", "op",
                                         "b", 10.0, now=100.0).acquired
                return out

            def __getattr__(self, name):
                return getattr(self.inner, name)

        res = L.try_acquire(Racer(cluster), "kubeflow", "op", "a", 10.0,
                            now=100.0)
        assert not res.acquired and res.reason == "lost-race"
        rec = L.lease_record(cluster.get(L.LEASE_API_VERSION,
                                         L.LEASE_KIND, "kubeflow", "op"))
        assert rec.holder == "b" and rec.transitions == 2

    def test_release_frees_the_lease_immediately(self):
        cluster = FakeCluster()
        L.try_acquire(cluster, "kubeflow", "op", "a", 300.0, now=100.0)
        assert L.release(cluster, "kubeflow", "op", "a")
        res = L.try_acquire(cluster, "kubeflow", "op", "b", 300.0,
                            now=101.0)
        assert res.acquired   # no waiting out the 300s duration
        # releasing a lease someone else holds is a no-op
        assert not L.release(cluster, "kubeflow", "op", "a")

    def test_malformed_lease_reads_as_free(self):
        cluster = FakeCluster()
        cluster.create({"apiVersion": L.LEASE_API_VERSION,
                        "kind": L.LEASE_KIND,
                        "metadata": {"name": "op",
                                     "namespace": "kubeflow"},
                        "spec": {L.DURATION_FIELD: "garbage"}})
        assert L.try_acquire(cluster, "kubeflow", "op", "a", 10.0,
                             now=100.0).acquired


class TestLeaderElector:
    def test_leader_follows_and_fails_over(self):
        cluster = FakeCluster()
        chaos_a = ControllerChaos(cluster)
        a = L.LeaderElector(client=chaos_a, identity="a", name="op",
                            duration_s=0.2)
        b = L.LeaderElector(client=cluster, identity="b", name="op",
                            duration_s=0.2)
        assert a.ensure() and not b.ensure()
        # a dies (its client raises everywhere): no renew possible —
        # local expiry demotes it, b steals after the duration
        chaos_a.kill()
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and not b.ensure():
            time.sleep(0.05)
        assert b.is_leader and not a.ensure()
        assert b.token > a.token   # the fencing token moved on

    def test_graceful_release_hands_over_without_waiting(self):
        cluster = FakeCluster()
        a = L.LeaderElector(client=cluster, identity="a", name="op",
                            duration_s=300.0)
        b = L.LeaderElector(client=cluster, identity="b", name="op",
                            duration_s=300.0, renew_every_s=0.01)
        assert a.ensure() and not b.ensure()
        a.release()
        time.sleep(0.02)
        assert b.ensure()   # immediately, not after 300s


class TestFencedClient:
    def test_non_leader_mutations_rejected_reads_pass(self):
        cluster = FakeCluster()
        cluster.create(tpujob_manifest())
        follower = L.LeaderElector(client=cluster, identity="b",
                                   name="op", duration_s=0.2)
        # someone else holds the lease
        L.try_acquire(cluster, "kubeflow", "op", "a", 300.0)
        follower.ensure()
        fenced = L.FencedKubeClient(cluster, follower)
        assert fenced.get(TPU_AV, "TPUJob", "kubeflow", "train")
        assert fenced.list(TPU_AV, "TPUJob")
        with pytest.raises(L.FencingError):
            fenced.patch(TPU_AV, "TPUJob", "kubeflow", "train",
                         {"metadata": {"annotations": {"x": "1"}}})
        with pytest.raises(L.FencingError):
            fenced.delete(TPU_AV, "TPUJob", "kubeflow", "train")
        assert fenced.rejected == 2
        # nothing reached the cluster
        job = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        assert "x" not in k8s.annotations_of(job)


# ----------------------------------------------- conflict-safe writes


class InterleavingClient:
    """Wrapper that fires a competing write immediately BEFORE the
    caller's first update of the target object: the caller's
    resourceVersion is then guaranteed stale, forcing the
    ConflictError → re-read → re-apply path every migrated RMW site
    must survive. ``compete(inner, obj)`` runs exactly once. (Plain
    class, not a KubeClient subclass — the base stubs would shadow
    __getattr__ delegation.)"""

    def __init__(self, inner, kind, name, compete):
        self.inner = inner
        self._kind, self._name = kind, name
        self._compete = compete
        self.fired = False

    def update(self, obj):
        if not self.fired and obj.get("kind") == self._kind and \
                k8s.name_of(obj) == self._name:
            self.fired = True
            self._compete(self.inner, obj)
        return self.inner.update(obj)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def _competing_annotation(inner, obj):
    """The competitor: a full-object update stamping its own annotation
    (what another controller replica's conflict-free write looks
    like). The site under test must retry and PRESERVE this."""
    fresh = inner.get(*k8s.key_of(obj))
    fresh.setdefault("metadata", {}).setdefault(
        "annotations", {})["competitor/wrote"] = "1"
    inner.update(fresh)


class TestUpdateWithConflictRetry:
    def test_retries_preserve_both_writers(self):
        cluster = FakeCluster()
        cluster.create(tpujob_manifest())
        client = InterleavingClient(cluster, "TPUJob", "train",
                                    _competing_annotation)
        before = obsreg.counter(
            "kftpu_conflict_retries_total",
            "read-modify-write attempts retried after a "
            "resourceVersion conflict", labels=("kind",)).labels(
                kind="TPUJob").value
        update_with_conflict_retry(
            client, TPU_AV, "TPUJob", "kubeflow", "train",
            lambda obj: apply_annotations(obj, {"mine": "yes"}))
        job = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        anns = k8s.annotations_of(job)
        assert anns["mine"] == "yes"
        assert anns["competitor/wrote"] == "1"   # nothing lost
        after = obsreg.counter(
            "kftpu_conflict_retries_total",
            "read-modify-write attempts retried after a "
            "resourceVersion conflict", labels=("kind",)).labels(
                kind="TPUJob").value
        assert after == before + 1

    def test_none_skips_the_write(self):
        cluster = FakeCluster()
        cluster.create(tpujob_manifest())
        rv = cluster.get(TPU_AV, "TPUJob", "kubeflow",
                         "train")["metadata"]["resourceVersion"]
        update_with_conflict_retry(cluster, TPU_AV, "TPUJob", "kubeflow",
                                   "train", lambda obj: None)
        assert cluster.get(TPU_AV, "TPUJob", "kubeflow",
                           "train")["metadata"]["resourceVersion"] == rv

    def test_not_found_propagates(self):
        with pytest.raises(NotFoundError):
            update_with_conflict_retry(FakeCluster(), TPU_AV, "TPUJob",
                                       "kubeflow", "gone",
                                       lambda obj: obj)

    def test_persistent_conflict_raises_after_budget(self):
        cluster = FakeCluster()
        cluster.create(tpujob_manifest())

        class AlwaysConflict:
            def __init__(self, inner):
                self.inner = inner

            def get(self, *a, **kw):
                out = self.inner.get(*a, **kw)
                _competing_annotation(self.inner, out)
                return out

            def __getattr__(self, name):
                return getattr(self.inner, name)

        with pytest.raises(ConflictError):
            update_with_conflict_retry(
                AlwaysConflict(cluster), TPU_AV, "TPUJob", "kubeflow",
                "train",
                lambda obj: apply_annotations(obj, {"mine": "1"}),
                max_attempts=3)


def _make_operator_env(scheduled=False):
    cluster = FakeCluster()
    cluster.add_tpu_slice_nodes("v5e-8")
    mgr = Manager(cluster)
    ctrl = mgr.add(TrainingJobReconciler("TPUJob"))
    cluster.create(tpujob_manifest(scheduled=scheduled))
    if scheduled:
        mgr.add(SliceScheduler(SchedulerConfig()))
    drive(cluster, mgr)
    return cluster, mgr, ctrl


class TestMigratedSitesTwoWriterInterleaving:
    """One test per migrated RMW writer: a competitor lands between the
    site's read and write; the site must retry and both updates must
    survive (acceptance criterion: no lost update, anywhere)."""

    def test_operator_restart_count(self):
        cluster, mgr, ctrl = _make_operator_env()
        # the competitor bumps the restart count itself — the classic
        # double-writer counter race (two operator replicas, a brief
        # two-leader window)
        def compete(inner, obj):
            fresh = inner.get(*k8s.key_of(obj))
            anns = fresh.setdefault("metadata", {}).setdefault(
                "annotations", {})
            anns[RESTART_COUNT_ANNOTATION] = str(int(
                anns.get(RESTART_COUNT_ANNOTATION, "0")) + 1)
            anns["competitor/wrote"] = "1"
            inner.update(fresh)

        ctrl.client = InterleavingClient(cluster, "TPUJob", "train",
                                         compete)
        cluster.fail_pod("kubeflow", "train-worker-0-1", "chaos: died")
        drive(cluster, mgr, ticks=6)
        job = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        anns = k8s.annotations_of(job)
        # competitor's +1 AND the operator's +1 both landed: 2, not 1
        assert anns[RESTART_COUNT_ANNOTATION] == "2"
        assert anns["competitor/wrote"] == "1"

    def test_operator_gang_shape_write_preserves_competitor(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        ctrl = mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob_manifest())
        ctrl.client = InterleavingClient(cluster, "TPUJob", "train",
                                         _competing_annotation)
        drive(cluster, mgr)
        job = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        anns = k8s.annotations_of(job)
        assert "kubeflow.org/gang-shape" in anns
        assert anns["competitor/wrote"] == "1"

    def test_scheduler_binding_write_preserves_competitor(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        sched = SliceScheduler(SchedulerConfig())
        cluster.create(tpujob_manifest(scheduled=True))
        client = InterleavingClient(cluster, "TPUJob", "train",
                                    _competing_annotation)
        sched.reconcile(client, ("", "#cluster-pass"))
        job = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        anns = k8s.annotations_of(job)
        assert BINDING_ANNOTATION in anns        # the bind landed
        assert anns["competitor/wrote"] == "1"   # and lost nothing

    def test_scheduler_preempt_count(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        sched = SliceScheduler(SchedulerConfig())
        cluster.create(tpujob_manifest(scheduled=True))
        sched.reconcile(cluster, ("", "#cluster-pass"))

        def compete(inner, obj):
            fresh = inner.get(*k8s.key_of(obj))
            anns = fresh.setdefault("metadata", {}).setdefault(
                "annotations", {})
            anns[PREEMPTED_COUNT_ANNOTATION] = str(int(
                anns.get(PREEMPTED_COUNT_ANNOTATION, "0")) + 1)
            inner.update(fresh)

        client = InterleavingClient(cluster, "TPUJob", "train", compete)
        manifest = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        sched._apply_preempt(client, manifest)
        job = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        # both increments landed: the preemption cannot be miscounted
        assert k8s.annotations_of(job)[PREEMPTED_COUNT_ANNOTATION] == "2"

    def test_scheduler_resize_history_append(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        sched = SliceScheduler(SchedulerConfig())
        cluster.create(tpujob_manifest(scheduled=True))
        sched.reconcile(cluster, ("", "#cluster-pass"))
        manifest = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        placement = binding_of(manifest)

        def compete(inner, obj):
            import json as _json
            fresh = inner.get(*k8s.key_of(obj))
            fresh.setdefault("metadata", {}).setdefault(
                "annotations", {})[RESIZE_HISTORY_ANNOTATION] = \
                _json.dumps([{"time": 1.0, "fromChips": 8,
                              "toChips": 4, "reason": "competitor"}])
            inner.update(fresh)

        client = InterleavingClient(cluster, "TPUJob", "train", compete)
        sched._apply_resize(client, manifest, placement, placement,
                            "grow: test")
        from kubeflow_tpu.scheduler.queue import resize_history
        hist = resize_history(cluster.get(TPU_AV, "TPUJob", "kubeflow",
                                          "train"))
        # the competitor's entry AND ours, in order — append, not clobber
        assert [h["reason"] for h in hist] == ["competitor", "grow: test"]

    def test_health_fold_two_writers_both_land(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        node = k8s.name_of(cluster.list("v1", "Node")[0])

        def compete(inner, obj):
            # the other controller folds its own event first
            health.record_host_event(inner, node, health.EVENT_NOT_READY,
                                     now=100.0)

        client = InterleavingClient(cluster, "Node", node, compete)
        rec = health.record_host_event(client, node,
                                       health.EVENT_POD_CRASH, now=100.0)
        assert rec is not None
        # both weight-1.0 events present in the final record
        stored = health.health_of(cluster.get("v1", "Node", "", node))
        assert stored["events"] == 2
        assert stored["score"] == pytest.approx(2.0)

    def test_quarantine_write_preserves_concurrent_fold(self):
        from kubeflow_tpu.api.trainingjob import QUARANTINE_ANNOTATION
        from kubeflow_tpu.scheduler.health import HealthConfig
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        node = k8s.name_of(cluster.list("v1", "Node")[0])
        health.record_host_event(cluster, node, health.EVENT_POD_CRASH)
        health.record_host_event(cluster, node, health.EVENT_POD_CRASH)
        health.record_host_event(cluster, node, health.EVENT_POD_CRASH)
        sched = SliceScheduler(SchedulerConfig(health=HealthConfig(
            quarantine_threshold=2.0)))

        def compete(inner, obj):
            health.record_host_event(inner, node, health.EVENT_STALL)

        client = InterleavingClient(cluster, "Node", node, compete)
        sched.reconcile(client, ("", "#cluster-pass"))
        stored = cluster.get("v1", "Node", "", node)
        assert QUARANTINE_ANNOTATION in k8s.annotations_of(stored)
        # the concurrent fold survived the quarantine write
        assert health.health_of(stored)["events"] == 4

    def test_finalize_ledger_preserves_competitor(self, tmp_path,
                                                  monkeypatch):
        from kubeflow_tpu.obs.goodput import GOODPUT_ANNOTATION
        from kubeflow_tpu.obs.trace import (SPAN_PATH_ENV, SpanWriter,
                                            reset_default_tracers)
        span_path = str(tmp_path / "spans.jsonl")
        monkeypatch.setenv(SPAN_PATH_ENV, span_path)
        reset_default_tracers()
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        ctrl = mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob_manifest())
        drive(cluster, mgr)
        job = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        from kubeflow_tpu.obs.trace import TRACE_ID_ANNOTATION
        tid = k8s.annotations_of(job)[TRACE_ID_ANNOTATION]
        writer = SpanWriter(span_path, "worker")
        writer.emit("window", start=time.time() - 5.0, end=time.time(),
                    trace_id=tid)
        writer.close()
        ctrl.client = InterleavingClient(cluster, "TPUJob", "train",
                                         _competing_annotation)
        cluster.set_pod_phase("kubeflow", "train-worker-0-0",
                              "Succeeded")
        drive(cluster, mgr)
        reset_default_tracers()
        job = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        anns = k8s.annotations_of(job)
        assert GOODPUT_ANNOTATION in anns        # the ledger landed
        assert anns["competitor/wrote"] == "1"   # and lost nothing


# ------------------------------------ ConflictError at the wire boundary


class TestApiserverConflictContract:
    def test_stale_rv_409s_and_loser_rereads(self):
        """The contract update_with_conflict_retry is built on, pinned
        at the HTTP boundary independently of the helper: concurrent
        update with a stale resourceVersion 409s as ConflictError, the
        winner's write survives, the loser re-reads and succeeds."""
        from kubeflow_tpu.cluster.apiserver import ClusterAPIServer
        from kubeflow_tpu.cluster.http_client import HttpKubeClient
        backend = FakeCluster()
        server = ClusterAPIServer(backend, port=0)
        port = server.start()
        try:
            a = HttpKubeClient(f"http://127.0.0.1:{port}", retries=0)
            b = HttpKubeClient(f"http://127.0.0.1:{port}", retries=0)
            a.create(tpujob_manifest())
            obj_a = a.get(TPU_AV, "TPUJob", "kubeflow", "train")
            obj_b = b.get(TPU_AV, "TPUJob", "kubeflow", "train")
            apply_annotations(obj_a, {"writer": "a"})
            a.update(obj_a)               # the winner
            apply_annotations(obj_b, {"writer": "b"})
            with pytest.raises(ConflictError):
                b.update(obj_b)           # stale rv: 409, typed
            fresh = b.get(TPU_AV, "TPUJob", "kubeflow", "train")
            assert k8s.annotations_of(fresh)["writer"] == "a"
            apply_annotations(fresh, {"writer": "b"})
            b.update(fresh)               # re-read rv: accepted
            final = a.get(TPU_AV, "TPUJob", "kubeflow", "train")
            assert k8s.annotations_of(final)["writer"] == "b"
        finally:
            server.stop()

    def test_lease_round_trip_over_the_wire(self):
        """Leases are ordinary objects at the wire level: an HTTP
        replica can elect against the simulated apiserver."""
        from kubeflow_tpu.cluster.apiserver import ClusterAPIServer
        from kubeflow_tpu.cluster.http_client import HttpKubeClient
        backend = FakeCluster()
        server = ClusterAPIServer(backend, port=0)
        port = server.start()
        try:
            client = HttpKubeClient(f"http://127.0.0.1:{port}",
                                    retries=0)
            assert L.try_acquire(client, "kubeflow", "op", "a",
                                 10.0).acquired
            assert not L.try_acquire(client, "kubeflow", "op", "b",
                                     10.0).acquired
        finally:
            server.stop()


# ---------------------------------------------------- snapshot counters


class TestSnapshotCounters:
    def test_round_trip_preserves_uid_and_rv_counters(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        cluster.create(tpujob_manifest())
        # a delete advances rv past any live object's
        cluster.delete(TPU_AV, "TPUJob", "kubeflow", "train")
        uid_n, rv_n = cluster._uid_n, cluster._rv_n
        restored = FakeCluster.from_snapshot(cluster.to_snapshot())
        assert (restored._uid_n, restored._rv_n) == (uid_n, rv_n)
        created = restored.create(tpujob_manifest(name="after"))
        # a restored control plane must never re-mint uid-1 (trace-id
        # collisions) or re-issue seen resourceVersions (orderings)
        assert created["metadata"]["uid"] == f"uid-{uid_n + 1}"
        assert int(created["metadata"]["resourceVersion"]) == rv_n + 1

    def test_legacy_snapshot_without_counters_derives_high_water(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        cluster.create(tpujob_manifest())
        snap = cluster.to_snapshot()
        del snap["counters"]
        restored = FakeCluster.from_snapshot(snap)
        existing_uids = {o["metadata"]["uid"]
                         for o in snap["objects"]}
        created = restored.create(tpujob_manifest(name="after"))
        assert created["metadata"]["uid"] not in existing_uids
        max_rv = max(int(o["metadata"]["resourceVersion"])
                     for o in snap["objects"])
        assert int(created["metadata"]["resourceVersion"]) > max_rv

    def test_apiserver_rv_high_water_survives_restore(self):
        from kubeflow_tpu.cluster.apiserver import ClusterAPIServer
        cluster = FakeCluster()
        cluster.create(tpujob_manifest())
        restored = FakeCluster.from_snapshot(cluster.to_snapshot())
        server = ClusterAPIServer(restored, port=0)
        assert server.current_rv() == cluster._rv_n


# --------------------------------------------------- controller gating


class TestControllerLeaderGating:
    def _replica(self, cluster, ident, duration=0.25):
        chaos = ControllerChaos(cluster)
        recorder = RecordingKubeClient(chaos)
        elector = L.LeaderElector(client=chaos, identity=ident,
                                  name="op", duration_s=duration)
        fenced = L.FencedKubeClient(recorder, elector)
        ctrl = Controller(reconciler=TrainingJobReconciler("TPUJob"),
                          client=fenced, elector=elector,
                          retry_backoff_s=0.01, retry_backoff_max_s=0.1)
        ctrl.bind_watches()
        ctrl.enqueue_existing()
        return chaos, recorder, elector, ctrl

    def test_standby_watches_but_writes_nothing(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        cluster.create(tpujob_manifest())
        _, rec_a, el_a, ctrl_a = self._replica(cluster, "a")
        _, rec_b, el_b, ctrl_b = self._replica(cluster, "b")
        for _ in range(4):
            ctrl_a.run_pending()
            ctrl_b.run_pending()
            cluster.tick()
        assert el_a.is_leader and not el_b.is_leader
        assert len(rec_a.mutations) > 0          # the leader drove
        assert rec_b.mutations == []             # the standby wrote zero
        assert len(cluster.list("v1", "Pod", "kubeflow")) == 2
        ctrl_a.stop()
        ctrl_b.stop()

    def test_failover_standby_adopts_and_finishes(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        cluster.create(tpujob_manifest())
        chaos_a, _, el_a, ctrl_a = self._replica(cluster, "a")
        _, rec_b, el_b, ctrl_b = self._replica(cluster, "b")
        for _ in range(4):
            ctrl_a.run_pending()
            ctrl_b.run_pending()
            cluster.tick()
        assert el_a.is_leader
        # leader process dies; standby must take over and recover the
        # failed gang
        chaos_a.kill()
        ctrl_a.stop()
        cluster.fail_pod("kubeflow", "train-worker-0-1", "chaos: died")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            ctrl_b.run_pending()
            cluster.tick()
            pods = [p for p in cluster.list("v1", "Pod", "kubeflow")
                    if p.get("status", {}).get("phase") == "Running"]
            if el_b.is_leader and len(pods) == 2:
                break
            time.sleep(0.02)
        assert el_b.is_leader
        assert len(rec_b.mutations) > 0
        job = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        assert k8s.annotations_of(job)[RESTART_COUNT_ANNOTATION] == "1"
        ctrl_b.stop()


# ------------------------------------------------- error-requeue backoff


class TestErrorRequeueBackoff:
    class Failing:
        primary = (TPU_AV, "TPUJob")
        owns = []
        controller_name = "failing"

        def __init__(self):
            self.calls = 0

        def reconcile(self, client, key):
            self.calls += 1
            raise RuntimeError("doomed")

        def map_event(self, client, obj):
            return []

    def test_retries_are_delayed_not_hot_looped(self):
        cluster = FakeCluster()
        cluster.create(tpujob_manifest())
        rec = self.Failing()
        ctrl = Controller(reconciler=rec, client=cluster,
                          retry_backoff_s=0.2, retry_backoff_max_s=5.0)
        ctrl.queue.add(("kubeflow", "train"))
        assert ctrl.process_one()
        assert rec.calls == 1
        # the retry is in _delayed, NOT immediately back in the queue
        assert len(ctrl.queue) == 0
        assert len(ctrl._delayed) == 1
        due, _key = ctrl._delayed[0]
        assert due > time.monotonic()   # genuinely in the future

    def test_backoff_grows_and_exhaustion_counts(self):
        cluster = FakeCluster()
        cluster.create(tpujob_manifest())
        rec = self.Failing()
        ctrl = Controller(reconciler=rec, client=cluster, max_retries=3,
                          retry_backoff_s=0.01, retry_backoff_max_s=1.0)
        exhausted = obsreg.counter(
            "kftpu_reconcile_retries_exhausted_total",
            "keys given up on after max_retries failed reconciles "
            "(invisible to alerting as a log line; the blind resync is "
            "the only later recovery)",
            labels=("controller",)).labels(controller="failing")
        before = exhausted.value
        delays = []
        ctrl.queue.add(("kubeflow", "train"))
        for _ in range(10):
            if not ctrl.process_one():
                if not ctrl._delayed:
                    break
                due, _k = ctrl._delayed[0]
                delays.append(due - time.monotonic())
                time.sleep(max(0.0, due - time.monotonic()) + 0.005)
                ctrl.pump_events()
        assert rec.calls == 4                     # initial + 3 retries
        assert exhausted.value == before + 1    # the give-up is visible
        # exponential: each recorded delay at least the previous one
        # (jitter is within [1, 1.5) of a doubling base)
        assert all(b > a for a, b in zip(delays, delays[1:]))


# ------------------------------------------------------------ orphan GC


class TestOrphanReconciliation:
    def test_orphan_pods_of_a_gone_job_are_reaped(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        ctrl = mgr.add(TrainingJobReconciler("TPUJob"))
        # orphans: pods carrying the job labels + an owner reference to
        # a job that no longer exists (a stale reconcile created them
        # just after the cascade ran)
        for i in range(2):
            cluster.create({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {
                    "name": f"ghost-worker-0-{i}", "namespace": "kubeflow",
                    "labels": {"kubeflow.org/job-name": "ghost",
                               "kubeflow.org/job-kind": "tpujob"},
                    "ownerReferences": [{
                        "apiVersion": TPU_AV, "kind": "TPUJob",
                        "name": "ghost", "uid": "uid-999",
                        "controller": True}]},
                "spec": {"containers": [{"name": "jax", "image": "x"}]},
            })
        drive(cluster, mgr)   # the pods' own events map to the gone owner
        assert cluster.list("v1", "Pod", "kubeflow") == []
        ctrl.stop()

    def test_live_jobs_pods_are_untouched(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob_manifest())
        drive(cluster, mgr)
        assert len(cluster.list("v1", "Pod", "kubeflow")) == 2


# --------------------------------------------------- controller chaos


class TestControllerChaos:
    def test_die_after_lands_the_write_then_kills(self):
        cluster = FakeCluster()
        chaos = ControllerChaos(cluster)
        chaos.die_after("create", 1)
        with pytest.raises(ControllerCrash):
            chaos.create(tpujob_manifest())
        # the write LANDED before the death — crash consistency, not
        # write loss
        assert cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        with pytest.raises(ControllerCrash):
            chaos.list("v1", "Pod")   # dead means dead
        chaos.revive()
        assert chaos.list(TPU_AV, "TPUJob")

    def test_partition_raises_everything_then_heals(self):
        cluster = FakeCluster()
        cluster.create(tpujob_manifest())
        chaos = ControllerChaos(cluster)
        chaos.partition(0.15)
        with pytest.raises(TransientAPIError):
            chaos.get(TPU_AV, "TPUJob", "kubeflow", "train")
        with pytest.raises(TransientAPIError):
            chaos.list("v1", "Pod")
        time.sleep(0.2)
        assert chaos.get(TPU_AV, "TPUJob", "kubeflow", "train")

    def test_die_mid_gang_create_successor_adopts_half_gang(self):
        """The operator dies after creating ONE pod of a two-pod gang;
        a fresh controller (in-memory state lost) must complete the
        gang — exactly once, no duplicates."""
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        chaos = ControllerChaos(cluster)
        ctrl = Controller(reconciler=TrainingJobReconciler("TPUJob"),
                          client=chaos, retry_backoff_s=0.01,
                          retry_backoff_max_s=0.05)
        ctrl.bind_watches()
        cluster.create(tpujob_manifest())
        ctrl.enqueue_existing()
        # service create is call 1; pod 1 is create call 2 — die there
        chaos.die_after("create", 2)
        for _ in range(6):
            ctrl.run_pending()
            cluster.tick()
        assert chaos.dead
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert len(pods) == 1                     # the half-created gang
        ctrl.stop()
        # the successor: fresh process over the same cluster
        ctrl2 = Controller(reconciler=TrainingJobReconciler("TPUJob"),
                           client=cluster)
        ctrl2.bind_watches()
        ctrl2.enqueue_existing()
        for _ in range(4):
            ctrl2.run_pending()
            cluster.tick()
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert sorted(k8s.name_of(p) for p in pods) == \
            ["train-worker-0-0", "train-worker-0-1"]
        ctrl2.stop()

    def test_scheduler_dies_after_binding_write_no_rewrite(self):
        """Kill the scheduler right after its binding write lands (the
        'between binding write and pod create' window): the successor
        must ADOPT the binding — zero rewrites — and the operator
        creates the gang on it."""
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        chaos = ControllerChaos(cluster)
        sched = SliceScheduler(SchedulerConfig())
        cluster.create(tpujob_manifest(scheduled=True))
        chaos.die_after("update", 1)   # the binding write is an update
        with pytest.raises(Exception):
            sched.reconcile(chaos, ("", "#cluster-pass"))
        manifest = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        assert binding_of(manifest) is not None   # the write landed
        # successor scheduler (fresh state) + the operator
        mgr = Manager(cluster)
        mgr.add(SliceScheduler(SchedulerConfig()))
        mgr.add(TrainingJobReconciler("TPUJob"))
        drive(cluster, mgr)
        fresh = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        assert binding_of(fresh).to_dict() == \
            binding_of(manifest).to_dict()        # adopted, not replanned
        anns_before = k8s.annotations_of(manifest)[BINDING_ANNOTATION]
        assert k8s.annotations_of(fresh)[BINDING_ANNOTATION] == \
            anns_before
        assert len(cluster.list("v1", "Pod", "kubeflow")) == 2

    def test_stale_watch_rewind_is_a_no_op(self):
        """Replayed stale events re-enqueue keys; level-triggered
        reconciles read fresh state and write NOTHING."""
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        chaos = ControllerChaos(cluster)
        recorder = RecordingKubeClient(chaos, ignore_kinds=())
        ctrl = Controller(reconciler=TrainingJobReconciler("TPUJob"),
                          client=recorder)
        ctrl.bind_watches()
        cluster.create(tpujob_manifest())
        ctrl.enqueue_existing()
        for _ in range(4):
            ctrl.run_pending()
            cluster.tick()
        writes_before = len(recorder.mutations)
        assert chaos.rewind_watch() > 0
        for _ in range(3):
            ctrl.run_pending()
            cluster.tick()
        assert len(recorder.mutations) == writes_before
        ctrl.stop()


# ------------------------------------------------------ split brain


class TestSplitBrain:
    def test_drill_fences_the_deposed_leader(self):
        from kubeflow_tpu.scheduler.soak import split_brain_drill
        report = split_brain_drill(lease_duration_s=0.25)
        assert report["initial_leader_elected"]
        assert report["stolen_by_standby"]
        assert report["old_leader_demoted"]
        assert report["fenced_write_rejected"]
        assert report["old_leader_writes_after_steal"] == 0
        assert not report["zombie_write_landed"]
        assert report["doubled_pod_creates"] == 0


# ----------------------------------------------------------- the soak


@pytest.mark.slow
class TestControlPlaneSoak:
    def test_soak_survives_kills_and_partition(self, tmp_path):
        from kubeflow_tpu.scheduler.soak import ControlPlaneSoak
        report = ControlPlaneSoak(
            workdir=str(tmp_path), total_steps=5, operator_kills=1,
            scheduler_kills=1, partitions=1,
            wall_budget_s=240.0).run()
        assert report["outcome"] == "succeeded"
        assert report["failovers"]["operator"] >= 1
        assert report["failovers"]["scheduler"] >= 1
        assert report["partitions"] == 1
        assert report["duplicate_pod_creates"] == 0
        assert not report["lost_annotation_writes"]
        assert report["never_leader_mutations"] == 0
        assert report["failover_s"]


# --------------------------------------------------------- concurrency


class TestConcurrentRMWThreads:
    def test_eight_threads_incrementing_lose_nothing(self):
        """The end-to-end lost-update test: N threads each increment a
        counter annotation M times through update_with_conflict_retry;
        the final value must be exactly N*M."""
        cluster = FakeCluster()
        cluster.create(tpujob_manifest())
        n_threads, n_incr = 8, 5

        def worker():
            for _ in range(n_incr):
                def mutate(obj):
                    anns = k8s.annotations_of(obj)
                    return apply_annotations(obj, {
                        "count": str(int(anns.get("count", "0")) + 1)})
                update_with_conflict_retry(
                    cluster, TPU_AV, "TPUJob", "kubeflow", "train",
                    mutate, max_attempts=200)

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        job = cluster.get(TPU_AV, "TPUJob", "kubeflow", "train")
        assert k8s.annotations_of(job)["count"] == str(n_threads * n_incr)
