"""Envtest-style tests for the training-job operator.

The pattern mirrors the reference's controller tests against envtest
(profile_controller_test.go reconcile-assertion pattern, SURVEY.md §4 tier 2),
with the scheduler modeled too so gang semantics are testable (the reference
could only exercise kube-batch E2E).
"""

import json

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.cluster.fake import POD_GROUP_LABEL, TPU_RESOURCE
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import (JAX_COORD_PORT,
                                             TrainingJobReconciler)


def tpujob_manifest(name="train", topology="v5e-8", num_slices=1, **spec_extra):
    return {
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": name, "namespace": "kubeflow"},
        "spec": {
            "replicaSpecs": {
                "TPU": {"tpuTopology": topology, "numSlices": num_slices,
                        "template": {"spec": {"containers": [
                            {"name": "jax", "image": "trainer:v1"}]}}},
            },
            "runPolicy": {"backoffLimit": 2},
            **spec_extra,
        },
    }


@pytest.fixture(params=["direct", "http"])
def env(request):
    """The whole matrix runs twice: against FakeCluster directly and over
    the real HTTP wire (client → apiserver → FakeCluster), so the
    wire path carries the same reconciler semantics (_http_env.py)."""
    from _http_env import make_env_cluster
    cluster, cleanup = make_env_cluster(request.param)
    cluster.add_tpu_slice_nodes("v5e-8")
    mgr = Manager(cluster)
    ctrl = mgr.add(TrainingJobReconciler("TPUJob"))
    yield cluster, mgr, ctrl
    for c in mgr.controllers:
        c.stop()
    cleanup()


def drive(cluster, mgr, ticks=3):
    for _ in range(ticks):
        mgr.run_pending()
        cluster.tick()
    mgr.run_pending()


class TestTPUJobReconcile:
    def test_creates_gang_and_service(self, env):
        cluster, mgr, _ = env
        cluster.create(tpujob_manifest())
        mgr.run_pending()
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert len(pods) == 2  # v5e-8 = 2 hosts
        names = {k8s.name_of(p) for p in pods}
        assert names == {"train-worker-0-0", "train-worker-0-1"}
        svc = cluster.get("v1", "Service", "kubeflow", "train-workers")
        assert svc["spec"]["clusterIP"] == "None"
        for p in pods:
            assert p["metadata"]["labels"][POD_GROUP_LABEL]
            limits = p["spec"]["containers"][0]["resources"]["limits"]
            assert limits[TPU_RESOURCE] == 4
            env_map = {e["name"]: e["value"]
                       for e in p["spec"]["containers"][0]["env"]}
            assert env_map["KFTPU_NUM_PROCESSES"] == "2"
            assert f":{JAX_COORD_PORT}" in env_map["KFTPU_COORDINATOR_ADDRESS"]
            sharding = json.loads(env_map["KFTPU_SHARDING"])
            assert sharding["data"] == 8

    def test_running_condition_after_schedule(self, env):
        cluster, mgr, _ = env
        cluster.create(tpujob_manifest())
        drive(cluster, mgr)
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow", "train")
        assert k8s.condition_true(job, "Running")
        assert job["status"]["replicaStatuses"]["tpu"]["active"] == 2

    def test_steady_state_reconcile_writes_status_once(self):
        # Running condition + replicaStatuses land in ONE update_status
        # per pass (single-update-per-reconcile idiom); a repeat pass
        # with nothing changed writes nothing at all
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob_manifest())
        drive(cluster, mgr)
        writes = []
        orig = cluster.update_status
        cluster.update_status = lambda obj: (writes.append(
            k8s.name_of(obj)), orig(obj))[1]
        try:
            rec = TrainingJobReconciler("TPUJob")
            rec.reconcile(cluster, ("kubeflow", "train"))
            assert len(writes) <= 1
            job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                              "kubeflow", "train")
            assert k8s.condition_true(job, "Running")
            assert job["status"]["replicaStatuses"]["tpu"]["active"] == 2
            writes.clear()
            rec.reconcile(cluster, ("kubeflow", "train"))
            assert writes == []
        finally:
            cluster.update_status = orig

    def test_chief_success_completes_job_and_cleans_running_pods(self, env):
        cluster, mgr, _ = env
        cluster.create(tpujob_manifest())
        drive(cluster, mgr)
        cluster.set_pod_phase("kubeflow", "train-worker-0-0", "Succeeded")
        mgr.run_pending()
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow", "train")
        assert k8s.condition_true(job, "Succeeded")
        # cleanPodPolicy=Running (default): the still-running worker is reaped
        remaining = {k8s.name_of(p) for p in cluster.list("v1", "Pod", "kubeflow")}
        assert "train-worker-0-1" not in remaining

    def test_worker_failure_restarts_whole_gang(self, env):
        cluster, mgr, _ = env
        cluster.create(tpujob_manifest())
        drive(cluster, mgr)
        cluster.fail_pod("kubeflow", "train-worker-0-1")
        mgr.run_pending()
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow", "train")
        assert job["metadata"]["annotations"][
            "kubeflow.org/gang-restart-count"] == "1"
        # Restarting was raised for the delete/recreate gap and consumed
        # once the gang existed again (GangRecreated)
        cond = k8s.get_condition(job, "Restarting")
        assert cond is not None
        assert cond["status"] == "False" and cond["reason"] == "GangRecreated"
        # the whole gang was recreated (fresh pods, unscheduled)
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert len(pods) == 2
        assert all(p.get("status", {}).get("phase", "Pending") == "Pending" or
                   not p["spec"].get("nodeName") for p in pods)

    def test_gang_restart_sets_resume_from(self, env):
        """The checkpoint/resume loop (SURVEY §5): a job with checkpointDir
        that gang-restarts gets spec.resumeFrom set automatically, and the
        recreated pods carry KFTPU_RESUME_FROM."""
        cluster, mgr, _ = env
        cluster.create(tpujob_manifest(checkpointDir="/ckpt/train"))
        drive(cluster, mgr)
        # first gang: checkpoint dir rendered, no resume
        pod = cluster.get("v1", "Pod", "kubeflow", "train-worker-0-0")
        env_map = {e["name"]: e["value"]
                   for e in pod["spec"]["containers"][0]["env"]}
        assert env_map["KFTPU_CHECKPOINT_DIR"] == "/ckpt/train"
        assert "KFTPU_RESUME_FROM" not in env_map
        cluster.fail_pod("kubeflow", "train-worker-0-1")
        mgr.run_pending()
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                          "kubeflow", "train")
        assert job["spec"]["resumeFrom"] == "/ckpt/train"
        # recreated gang resumes from the job's own checkpoints
        pod = cluster.get("v1", "Pod", "kubeflow", "train-worker-0-0")
        env_map = {e["name"]: e["value"]
                   for e in pod["spec"]["containers"][0]["env"]}
        assert env_map["KFTPU_RESUME_FROM"] == "/ckpt/train"
        assert env_map["KFTPU_CHECKPOINT_DIR"] == "/ckpt/train"

    def test_vanished_gang_member_restarts_whole_gang(self, env):
        """Node loss / preemption DELETES the pod object — no Failed phase
        ever appears. The survivors' jax.distributed world cannot re-admit
        a fresh peer, so a partial disappearance must gang-restart (with
        resumeFrom), never recreate the missing pod solo."""
        cluster, mgr, _ = env
        cluster.create(tpujob_manifest(checkpointDir="/ckpt/train"))
        drive(cluster, mgr)
        cluster.delete("v1", "Pod", "kubeflow", "train-worker-0-1")
        mgr.run_pending()
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                          "kubeflow", "train")
        assert job["metadata"]["annotations"][
            "kubeflow.org/gang-restart-count"] == "1"
        assert job["spec"]["resumeFrom"] == "/ckpt/train"
        cond = k8s.get_condition(job, "Restarting")
        assert cond is not None and cond["reason"] in ("GangPodsVanished",
                                                       "GangRecreated")
        mgr.run_pending()
        # the FULL gang exists again (not just the vanished member)
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert {k8s.name_of(p) for p in pods} == \
            {"train-worker-0-0", "train-worker-0-1"}
        # and survivors were replaced too: a fresh jax.distributed world
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                          "kubeflow", "train")
        assert k8s.get_condition(job, "Restarting")["status"] == "False"

    def test_spec_resize_restarts_gang_without_backoff(self, env):
        """numSlices change mid-run: the old world size is baked into every
        survivor's env, so the gang restarts on the new shape — but as an
        operator action, not a failure (no backoff budget burned)."""
        cluster, mgr, _ = env
        cluster.add_tpu_slice_nodes("v5e-8", pool="tpu-pool-b")
        cluster.create(tpujob_manifest(checkpointDir="/ckpt/train"))
        drive(cluster, mgr)
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                          "kubeflow", "train")
        job["spec"]["replicaSpecs"]["TPU"]["numSlices"] = 2
        cluster.update(job)
        drive(cluster, mgr)
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                          "kubeflow", "train")
        # no failure accounting; resumeFrom set; gang-size re-recorded
        anns = k8s.annotations_of(job)
        assert "kubeflow.org/gang-restart-count" not in anns
        assert anns["kubeflow.org/gang-shape"] == "TPU:v5e-8x2"
        assert job["spec"]["resumeFrom"] == "/ckpt/train"
        pods = {k8s.name_of(p) for p in cluster.list("v1", "Pod",
                                                     "kubeflow")}
        assert pods == {"train-worker-0-0", "train-worker-0-1",
                        "train-worker-1-0", "train-worker-1-1"}
        # every pod (old names included) carries the NEW world size
        for p in cluster.list("v1", "Pod", "kubeflow"):
            env_map = {e["name"]: e["value"]
                       for e in p["spec"]["containers"][0]["env"]}
            assert env_map["KFTPU_NUM_PROCESSES"] == "4"

    def test_legacy_cpu_replica_recreated_solo(self, env):
        """CPU-only legacy kinds keep the reference operators' behavior: a
        deleted PS/worker pod is recreated individually (TF gRPC
        reconnects), NOT via gang restart."""
        cluster, mgr, _ = env
        mgr.add(TrainingJobReconciler("TFJob"))
        tmpl = {"spec": {"containers": [{"name": "tf", "image": "tf:1"}]}}
        cluster.create({
            "apiVersion": "kubeflow.org/v1beta2", "kind": "TFJob",
            "metadata": {"name": "legacy", "namespace": "kubeflow"},
            "spec": {"tfReplicaSpecs": {
                "Worker": {"replicas": 2, "template": tmpl},
                "PS": {"replicas": 1, "template": tmpl},
            }},
        })
        drive(cluster, mgr)
        cluster.delete("v1", "Pod", "kubeflow", "legacy-worker-1")
        mgr.run_pending()
        job = cluster.get("kubeflow.org/v1beta2", "TFJob", "kubeflow",
                          "legacy")
        assert not k8s.condition_true(job, "Restarting")
        assert "kubeflow.org/gang-restart-count" not in \
            k8s.annotations_of(job)
        pods = {k8s.name_of(p) for p in cluster.list("v1", "Pod",
                                                     "kubeflow")}
        assert "legacy-worker-1" in pods  # recreated solo
        assert len(pods) == 3

    def test_backoff_limit_fails_job(self, env):
        cluster, mgr, _ = env
        cluster.create(tpujob_manifest())
        for _ in range(3):
            drive(cluster, mgr)
            pods = cluster.list("v1", "Pod", "kubeflow")
            if not pods:
                break
            cluster.fail_pod("kubeflow", k8s.name_of(pods[-1]))
            mgr.run_pending()
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow", "train")
        assert k8s.condition_true(job, "Failed")
        reason = k8s.get_condition(job, "Failed")["reason"]
        assert reason == "BackoffLimitExceeded"

    def test_job_delete_cascades_to_pods(self, env):
        cluster, mgr, _ = env
        cluster.create(tpujob_manifest())
        mgr.run_pending()
        assert len(cluster.list("v1", "Pod", "kubeflow")) == 2
        cluster.delete("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow", "train")
        assert cluster.list("v1", "Pod", "kubeflow") == []

    def test_multislice_contract(self, env):
        cluster, mgr, _ = env
        cluster.add_tpu_slice_nodes("v5e-8", pool="pool2")
        cluster.create(tpujob_manifest(name="ms", num_slices=2))
        mgr.run_pending()
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert len(pods) == 4
        env_map = {}
        for p in pods:
            e = {x["name"]: x["value"] for x in p["spec"]["containers"][0]["env"]}
            env_map[k8s.name_of(p)] = e
        assert env_map["ms-worker-1-1"]["KFTPU_PROCESS_ID"] == "3"
        assert env_map["ms-worker-1-1"]["KFTPU_SLICE_ID"] == "1"
        assert env_map["ms-worker-0-0"]["KFTPU_NUM_PROCESSES"] == "4"
        coords = {e["KFTPU_COORDINATOR_ADDRESS"] for e in env_map.values()}
        assert len(coords) == 1  # one coordinator for the whole job


class TestLegacyKinds:
    def test_tfjob_renders_tf_config(self):
        cluster = FakeCluster()
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("TFJob"))
        cluster.create({
            "apiVersion": "kubeflow.org/v1beta2", "kind": "TFJob",
            "metadata": {"name": "tf", "namespace": "kubeflow"},
            "spec": {"tfReplicaSpecs": {
                "Chief": {"replicas": 1, "template": {
                    "spec": {"containers": [{"name": "tf", "image": "i"}]}}},
                "Worker": {"replicas": 2, "template": {
                    "spec": {"containers": [{"name": "tf", "image": "i"}]}}},
            }},
        })
        mgr.run_pending()
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert len(pods) == 3
        chief = cluster.get("v1", "Pod", "kubeflow", "tf-chief-0")
        cfg = json.loads({e["name"]: e["value"] for e in
                          chief["spec"]["containers"][0]["env"]}["TF_CONFIG"])
        assert cfg["task"] == {"type": "chief", "index": 0}
        assert len(cfg["cluster"]["worker"]) == 2
        assert cfg["cluster"]["chief"][0].startswith("tf-chief-0.tf-workers.kubeflow")

    def test_pytorchjob_renders_master_env(self):
        cluster = FakeCluster()
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("PyTorchJob"))
        cluster.create({
            "apiVersion": "kubeflow.org/v1beta2", "kind": "PyTorchJob",
            "metadata": {"name": "pt", "namespace": "kubeflow"},
            "spec": {"pytorchReplicaSpecs": {
                "Master": {"replicas": 1, "template": {
                    "spec": {"containers": [{"name": "t", "image": "i"}]}}},
                "Worker": {"replicas": 3, "template": {
                    "spec": {"containers": [{"name": "t", "image": "i"}]}}},
            }},
        })
        mgr.run_pending()
        w2 = cluster.get("v1", "Pod", "kubeflow", "pt-worker-2")
        env_map = {e["name"]: e["value"]
                   for e in w2["spec"]["containers"][0]["env"]}
        assert env_map["MASTER_ADDR"].startswith("pt-master-0.")
        assert env_map["RANK"] == "3" and env_map["WORLD_SIZE"] == "4"

    def test_mxjob_renders_dmlc_env(self):
        cluster = FakeCluster()
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("MXJob"))
        tmpl = {"spec": {"containers": [{"name": "t", "image": "i"}]}}
        cluster.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "MXJob",
            "metadata": {"name": "mx", "namespace": "kubeflow"},
            "spec": {"mxReplicaSpecs": {
                "Scheduler": {"replicas": 1, "template": tmpl},
                "Server": {"replicas": 2, "template": tmpl},
                "Worker": {"replicas": 2, "template": tmpl},
            }},
        })
        mgr.run_pending()
        w = cluster.get("v1", "Pod", "kubeflow", "mx-worker-1")
        env_map = {e["name"]: e["value"]
                   for e in w["spec"]["containers"][0]["env"]}
        assert env_map["DMLC_PS_ROOT_URI"].startswith("mx-scheduler-0.")
        assert env_map["DMLC_ROLE"] == "worker"
        assert env_map["DMLC_NUM_SERVER"] == "2"
        assert env_map["DMLC_NUM_WORKER"] == "2"
        # worker (not the long-running scheduler) completes the job
        cluster.tick()
        cluster.set_pod_phase("kubeflow", "mx-worker-0", "Succeeded")
        mgr.run_pending()
        job = cluster.get("kubeflow.org/v1alpha1", "MXJob", "kubeflow", "mx")
        assert k8s.condition_true(job, "Succeeded")

    def test_paddlejob_renders_paddle_env(self):
        cluster = FakeCluster()
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("PaddleJob"))
        tmpl = {"spec": {"containers": [{"name": "t", "image": "i"}]}}
        cluster.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "PaddleJob",
            "metadata": {"name": "pd", "namespace": "kubeflow"},
            "spec": {"paddleReplicaSpecs": {
                "Pserver": {"replicas": 2, "template": tmpl},
                "Trainer": {"replicas": 3, "template": tmpl},
            }},
        })
        mgr.run_pending()
        t = cluster.get("v1", "Pod", "kubeflow", "pd-trainer-2")
        env_map = {e["name"]: e["value"]
                   for e in t["spec"]["containers"][0]["env"]}
        assert env_map["PADDLE_TRAINING_ROLE"] == "TRAINER"
        assert env_map["PADDLE_TRAINER_ID"] == "2"
        assert env_map["PADDLE_TRAINERS"] == "3"
        assert "pd-pserver-0." in env_map["PADDLE_PSERVERS"]
        assert "pd-pserver-1." in env_map["PADDLE_PSERVERS"]
        ps = cluster.get("v1", "Pod", "kubeflow", "pd-pserver-0")
        ps_env = {e["name"]: e["value"]
                  for e in ps["spec"]["containers"][0]["env"]}
        assert ps_env["PADDLE_TRAINING_ROLE"] == "PSERVER"

    def test_chainerjob_renders_mpi_hostlist(self):
        cluster = FakeCluster()
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("ChainerJob"))
        tmpl = {"spec": {"containers": [{"name": "t", "image": "i"}]}}
        cluster.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "ChainerJob",
            "metadata": {"name": "ch", "namespace": "kubeflow"},
            "spec": {"chainerReplicaSpecs": {
                "Master": {"replicas": 1, "template": tmpl},
                "Worker": {"replicas": 2, "template": tmpl},
            }},
        })
        mgr.run_pending()
        m = cluster.get("v1", "Pod", "kubeflow", "ch-master-0")
        env_map = {e["name"]: e["value"]
                   for e in m["spec"]["containers"][0]["env"]}
        assert env_map["KFTPU_MPI_NUM_HOSTS"] == "2"
        assert "ch-worker-0." in env_map["KFTPU_MPI_HOSTS"]

    def test_chainerjob_tpu_replicas_get_hostlist(self):
        """A ChainerJob with a TPU gang: the master AND the TPU pods all
        carry the gang's hostlist (the gap a master-only render leaves)."""
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("ChainerJob"))
        tmpl = {"spec": {"containers": [{"name": "t", "image": "i"}]}}
        cluster.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "ChainerJob",
            "metadata": {"name": "cht", "namespace": "kubeflow"},
            "spec": {"chainerReplicaSpecs": {
                "Master": {"replicas": 1, "template": tmpl},
                "TPU": {"tpuTopology": "v5e-8", "template": tmpl},
            }},
        })
        mgr.run_pending()
        for pod_name in ("cht-master-0", "cht-worker-0-0", "cht-worker-0-1"):
            p = cluster.get("v1", "Pod", "kubeflow", pod_name)
            env_map = {e["name"]: e["value"]
                       for e in p["spec"]["containers"][0]["env"]}
            assert env_map["KFTPU_MPI_NUM_HOSTS"] == "2", pod_name
            assert "cht-worker-0-0." in env_map["KFTPU_MPI_HOSTS"], pod_name

    def test_all_kinds_accept_tpu_replicas(self):
        """The whole point of the build: every legacy kind gains the TPU
        replica type (BASELINE.json north star)."""
        from kubeflow_tpu.api.trainingjob import (API_VERSIONS, JOB_KINDS,
                                                  TrainingJob, _SPECS_KEY)
        for kind in JOB_KINDS:
            specs_key = _SPECS_KEY[kind]
            job = TrainingJob.from_manifest({
                "apiVersion": API_VERSIONS[kind], "kind": kind,
                "metadata": {"name": "j", "namespace": "kubeflow"},
                "spec": {specs_key: {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "w", "image": "i"}]}}}}}})
            assert job.tpu_spec is not None
            assert job.total_pods() == 2  # v5e-8 = 2 hosts

    def test_mpijob_tpu_shorthand_renders_hostlist(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-16")
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("MPIJob"))
        cluster.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "MPIJob",
            "metadata": {"name": "hvd", "namespace": "kubeflow"},
            "spec": {"tpuTopology": "v5e-16",
                     "template": {"spec": {"containers": [
                         {"name": "m", "image": "i"}]}}},
        })
        mgr.run_pending()
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert len(pods) == 4
        env_map = {e["name"]: e["value"]
                   for e in pods[0]["spec"]["containers"][0]["env"]}
        assert env_map["KFTPU_MPI_NUM_HOSTS"] == "4"
        assert env_map["KFTPU_MPI_HOSTS"].count(",") == 3
