"""Overlapped input pipeline (ISSUE 3): the multi-process augment ring
(data/mp_augment.py), device-side double-buffered prefetch
(data/device_prefetch.py), the async window-edge metrics fetch
(runtime/metrics.py AsyncWindowFetch), and the producer-crash
propagation regression in the threaded prefetchers.

The determinism contract under test: the multi-process path must yield
BYTE-identical batches to the single-thread path for a fixed seed, and
resuming from batch k must replay the exact remaining sequence — the
checkpoint-restart / chaos-parity guarantees ride on both.
"""

from __future__ import annotations

import multiprocessing as mp
import time

import numpy as np
import pytest

from kubeflow_tpu.data.imagenet import (ImageNetSource, record_bytes,
                                        write_shards)

SIZE = 16
N = 96
CLASSES = 10
B = 8


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    rng = np.random.default_rng(11)
    images = rng.integers(0, 256, (N, SIZE, SIZE, 3), dtype=np.uint8)
    labels = np.arange(N) % CLASSES
    d = tmp_path_factory.mktemp("imagenet-mp")
    write_shards(str(d), images, labels, shard_records=32,
                 num_classes=CLASSES)
    return str(d)


def _no_leaked_children(before: set) -> bool:
    """Every process we spawned is gone (ignores unrelated survivors)."""
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        now = {p.pid for p in mp.active_children()}
        if now <= before:
            return True
        time.sleep(0.05)
    return False


# -- satellite regression: producer crashes must fail the run ---------------

class TestProducerCrashPropagation:
    """A crashed producer used to end iteration silently — the epoch was
    truncated and the run 'succeeded' on partial data."""

    def test_prefetcher_propagates_producer_exception(self):
        from kubeflow_tpu.data.imagenet import _Prefetcher

        def gen():
            yield {"images": np.zeros(2), "labels": np.zeros(2)}
            raise ValueError("decode blew up")

        p = _Prefetcher(gen(), depth=2)
        it = iter(p)
        next(it)
        with pytest.raises(ValueError, match="decode blew up"):
            next(it)
        p.stop()

    def test_prefetcher_clean_eof_still_ends_iteration(self):
        from kubeflow_tpu.data.imagenet import _Prefetcher
        p = _Prefetcher(iter([{"x": 1}, {"x": 2}]), depth=2)
        assert [b["x"] for b in p] == [1, 2]
        p.stop()

    def test_prefetcher_death_without_eof_raises(self):
        from kubeflow_tpu.data.imagenet import _Prefetcher

        # a producer that dies without reporting (simulated: the tracked
        # outcome flags are never set, as when the thread is killed)
        p = _Prefetcher(iter([]), depth=2)
        p._thread.join(5)
        while not p._q.empty():  # the EOF sentinel a killed thread
            p._q.get_nowait()    # would never have queued
        p._done = False          # as if _produce never reached its epilogue
        with pytest.raises(RuntimeError, match="truncated epoch"):
            next(iter(p))
        p.stop()

    def test_py_record_pipeline_propagates_read_error(self, tmp_path):
        from kubeflow_tpu.data.pipeline import PyRecordPipeline
        shard = tmp_path / "a.rec"
        # 64 records / batch 2 = 32 batches >> the prefetch queue depth,
        # so the producer is guaranteed to still be reading (blocked on
        # backpressure) when the handles vanish under it
        shard.write_bytes(b"\0" * (record_bytes(SIZE) * 64))
        pipe = PyRecordPipeline([str(shard)], record_bytes(SIZE), 2, seed=1)
        # yank the file handle out from under the producer: the read
        # error must surface to the consumer, not truncate the epoch
        for f in pipe._files.values():
            f.close()
        with pytest.raises(Exception):
            list(pipe)
        pipe.close()


# -- determinism: mp path == single-thread path -----------------------------

class TestMpAugmentDeterminism:
    def _take(self, d, workers, start=0, k=8, **kw):
        src = ImageNetSource(d, batch_size=B, workers=workers, **kw)
        try:
            it = src.batches(seed=3, start_batch=start)
            return [{key: v.copy() for key, v in next(it).items()}
                    for _ in range(k)]
        finally:
            src.close()

    def test_byte_identical_to_single_thread_across_epochs(self, data_dir):
        # k=14 crosses the epoch boundary (96/8 = 12 batches/epoch), so
        # the per-(seed, epoch, index) augment seeding is pinned across
        # the reshuffle too
        ref = self._take(data_dir, workers=0, k=14)
        got = self._take(data_dir, workers=2, k=14)
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a["labels"], b["labels"])
            np.testing.assert_array_equal(a["images"], b["images"])
            assert a["images"].dtype == b["images"].dtype

    def test_resume_replays_exact_remaining_sequence(self, data_dir):
        ref = self._take(data_dir, workers=0, k=10)
        resumed = self._take(data_dir, workers=2, start=6, k=4)
        for a, b in zip(ref[6:], resumed):
            np.testing.assert_array_equal(a["labels"], b["labels"])
            np.testing.assert_array_equal(a["images"], b["images"])

    def test_uint8_output_mode_identical(self, data_dir):
        ref = self._take(data_dir, workers=0, k=4, output="uint8")
        got = self._take(data_dir, workers=2, k=4, output="uint8")
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a["images"], b["images"])
            assert b["images"].dtype == np.uint8


# -- AugmentPool lifecycle: errors, death, shutdown -------------------------

class TestAugmentPoolLifecycle:
    def test_close_leaves_no_worker_processes(self, data_dir):
        before = {p.pid for p in mp.active_children()}
        src = ImageNetSource(data_dir, batch_size=B, workers=2)
        it = src.batches(seed=1)
        next(it)
        assert len(mp.active_children()) > len(before)
        src.close()
        assert _no_leaked_children(before)

    def test_early_stop_mid_epoch_leaks_nothing(self, data_dir):
        # the worker loop's early-stop/preemption path: the consumer
        # abandons the stream mid-epoch and closes
        before = {p.pid for p in mp.active_children()}
        src = ImageNetSource(data_dir, batch_size=B, workers=2)
        for i, _ in enumerate(src.batches(seed=1)):
            if i >= 2:
                break
        src.close()
        assert _no_leaked_children(before)
        src.close()   # idempotent

    def test_feeder_exception_propagates(self):
        from kubeflow_tpu.data.mp_augment import AugmentPool

        def source():
            rng = np.random.default_rng(0)
            yield rng.integers(0, 256, (4, record_bytes(SIZE)),
                               dtype=np.uint8), 7
            raise RuntimeError("record reader failed")

        before = {p.pid for p in mp.active_children()}
        pool = AugmentPool(workers=1, batch_records=4,
                           record_bytes=record_bytes(SIZE),
                           image_size=SIZE, output="uint8")
        try:
            pool.start(source())
            it = iter(pool)
            batch = next(it)      # the batch submitted before the crash
            assert batch["images"].shape == (4, SIZE, SIZE, 3)
            with pytest.raises(RuntimeError, match="record reader failed"):
                next(it)
        finally:
            pool.close()
        assert _no_leaked_children(before)

    def test_worker_death_detected_not_hung(self, data_dir):
        src = ImageNetSource(data_dir, batch_size=B, workers=1)
        try:
            it = src.batches(seed=1)
            next(it)
            for p in src._mp_pool._procs:
                p.terminate()
                p.join(5)
            with pytest.raises(RuntimeError, match="died"):
                for _ in range(64):   # ring drains, then the check fires
                    next(it)
        finally:
            src.close()

    def test_oversized_batch_rejected(self):
        from kubeflow_tpu.data.mp_augment import AugmentPool
        pool = AugmentPool(workers=1, batch_records=2,
                           record_bytes=record_bytes(SIZE),
                           image_size=SIZE, output="uint8")
        try:
            pool.start(iter([(np.zeros((4, record_bytes(SIZE)), np.uint8),
                              0)]))
            with pytest.raises(ValueError, match="exceeds"):
                next(iter(pool))
        finally:
            pool.close()

    def test_bad_geometry_rejected(self):
        from kubeflow_tpu.data.mp_augment import AugmentPool
        with pytest.raises(ValueError, match="workers"):
            AugmentPool(workers=0, batch_records=2, record_bytes=8,
                        image_size=SIZE)
        with pytest.raises(ValueError, match="workers"):
            ImageNetSource("/nonexistent", batch_size=2, workers=-1)


# -- device prefetch --------------------------------------------------------

@pytest.mark.compute
class TestDevicePrefetcher:
    """On the 8-device CPU mesh: depth bound, sharded placement parity
    with place_batch, and shutdown draining."""

    def _mesh_place(self):
        import jax
        from jax.sharding import (Mesh, NamedSharding,
                                  PartitionSpec as P)
        mesh = Mesh(np.array(jax.devices()), ("data",))
        sharding = NamedSharding(mesh, P("data"))

        def place(b):
            return {k: jax.device_put(v, sharding) for k, v in b.items()}
        return place, sharding

    def _batches(self, n):
        for i in range(n):
            yield {"images": np.full((8, 4), i, np.float32),
                   "labels": np.arange(8, dtype=np.int32)}

    def test_depth_bounds_runahead_and_device_residency(self):
        from kubeflow_tpu.data.device_prefetch import DevicePrefetcher
        pulled = []

        def tracking():
            for i, b in enumerate(self._batches(10)):
                pulled.append(i)
                yield b

        place, _ = self._mesh_place()
        pf = DevicePrefetcher(tracking(), place, depth=3)
        got = next(pf)
        # exactly depth batches staged: one handed out, depth-1 in
        # flight, and the source never pulled further ahead — the HBM
        # bound the worker relies on
        assert len(pulled) == 3
        assert pf.in_flight == 2
        assert float(np.asarray(got["images"])[0, 0]) == 0.0
        for _ in range(9):
            next(pf)
        assert pf.in_flight == 0
        with pytest.raises(StopIteration):
            next(pf)

    def test_placement_matches_place_fn(self):
        from kubeflow_tpu.data.device_prefetch import DevicePrefetcher
        place, sharding = self._mesh_place()
        pf = DevicePrefetcher(self._batches(3), place, depth=2)
        batch = next(pf)
        direct = place(next(self._batches(1)))
        for k in batch:
            assert batch[k].sharding == direct[k].sharding
            assert batch[k].sharding == sharding
        pf.close()

    def test_close_drops_staged_batches(self):
        from kubeflow_tpu.data.device_prefetch import DevicePrefetcher
        place, _ = self._mesh_place()
        pf = DevicePrefetcher(self._batches(10), place, depth=4)
        next(pf)
        assert pf.in_flight == 3
        pf.close()
        assert pf.in_flight == 0
        with pytest.raises(StopIteration):
            next(pf)    # closed: no refill, no source pull

    def test_consumed_batches_are_not_retained(self):
        # the prefetcher must hand buffers off, never accumulate them:
        # device memory is bounded by depth, not by steps consumed
        import gc
        import weakref

        from kubeflow_tpu.data.device_prefetch import DevicePrefetcher
        place, _ = self._mesh_place()
        pf = DevicePrefetcher(self._batches(6), place, depth=2)
        refs = []
        for batch in pf:
            refs.append(weakref.ref(batch["images"]))
            del batch
        gc.collect()
        assert all(r() is None for r in refs)

    def test_depth_validated(self):
        from kubeflow_tpu.data.device_prefetch import DevicePrefetcher
        with pytest.raises(ValueError, match="depth"):
            DevicePrefetcher(iter([]), lambda b: b, depth=0)


# -- async window-edge metrics fetch ----------------------------------------

class _FakeDeviceValue:
    """Mimics a jax array's async device→host metric fetch surface."""

    def __init__(self, v):
        self.v = v
        self.copies_started = 0

    def copy_to_host_async(self):
        self.copies_started += 1

    def __float__(self):
        return float(self.v)


class TestAsyncWindowFetch:
    def test_lag_holds_newest_window_back(self):
        from kubeflow_tpu.runtime.metrics import AsyncWindowFetch
        af = AsyncWindowFetch(lag=1)
        af.submit(10, 10, 1.0, {"loss": _FakeDeviceValue(0.5)})
        assert af.drain() == []          # its copy may still be in flight
        assert af.pending == 1
        af.submit(20, 10, 1.0, {"loss": _FakeDeviceValue(0.25)})
        out = af.drain()
        assert [(s, vals["loss"]) for s, _, _, vals in out] == [(10, 0.5)]
        assert af.pending == 1

    def test_force_drains_everything_in_order(self):
        from kubeflow_tpu.runtime.metrics import AsyncWindowFetch
        af = AsyncWindowFetch(lag=2)
        for s in (5, 10, 15):
            af.submit(s, 5, 0.5, {"loss": _FakeDeviceValue(s)})
        out = af.drain(force=True)
        assert [s for s, *_ in out] == [5, 10, 15]
        assert af.pending == 0
        assert all(isinstance(vals["loss"], float)
                   for *_, vals in out)

    def test_submit_starts_the_device_copy(self):
        from kubeflow_tpu.runtime.metrics import AsyncWindowFetch
        af = AsyncWindowFetch(lag=1)
        v = _FakeDeviceValue(1.0)
        af.submit(1, 1, 0.1, {"loss": v, "lr": 0.5})
        assert v.copies_started == 1     # async copy began at submit
        _, _, _, vals = af.drain(force=True)[0]
        assert vals == {"loss": 1.0, "lr": 0.5}

    def test_lag_zero_is_the_blocking_edge_fetch(self):
        from kubeflow_tpu.runtime.metrics import AsyncWindowFetch
        af = AsyncWindowFetch(lag=0)
        af.submit(1, 1, 0.1, {"loss": _FakeDeviceValue(2.0)})
        assert len(af.drain()) == 1


# -- worker-loop integration ------------------------------------------------

@pytest.mark.slow
class TestWorkerIntegration:
    def test_mp_pipeline_numerics_match_default_path(self, data_dir):
        # the whole run is a function of (data, seed); the overlapped
        # pipeline must not change a single bit of it
        from kubeflow_tpu.runtime.worker import train
        kw = dict(workload="resnet50", steps=3, global_batch=8,
                  data_dir=data_dir, sync_every=1, seed=11)
        ref = train(input_workers=0, device_prefetch=0, **kw)
        got = train(input_workers=2, device_prefetch=2, **kw)
        assert got.steps == ref.steps == 3
        assert got.final_metrics["loss"] == pytest.approx(
            ref.final_metrics["loss"], abs=0, rel=0)

    def test_no_processes_leak_after_train(self, data_dir):
        from kubeflow_tpu.runtime.worker import train
        before = {p.pid for p in mp.active_children()}
        train(workload="resnet50", steps=2, global_batch=8,
              data_dir=data_dir, sync_every=1, seed=5,
              input_workers=2, device_prefetch=2)
        assert _no_leaked_children(before)

    def test_env_knobs_reach_train(self, data_dir, monkeypatch):
        from kubeflow_tpu.runtime.worker import train
        monkeypatch.setenv("KFTPU_INPUT_WORKERS", "not-a-number")
        with pytest.raises(ValueError, match="KFTPU_INPUT_WORKERS"):
            train(workload="resnet50", steps=1, global_batch=8,
                  data_dir=data_dir)
        monkeypatch.setenv("KFTPU_INPUT_WORKERS", "0")
        monkeypatch.setenv("KFTPU_DEVICE_PREFETCH", "-1")
        with pytest.raises(ValueError, match="device_prefetch"):
            train(workload="resnet50", steps=1, global_batch=8,
                  data_dir=data_dir)
