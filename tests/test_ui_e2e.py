"""Browser-flow E2E over the real wire: one auth ingress fronting the
central dashboard AND the jupyter web app, exercised exactly as the SPA
does it — 302 to login, cookie login, dashboard shell + bundle, notebook
spawn through /jupyter/, runs panel showing the cluster's training job.

The reference covers this surface only piecemeal (kflogin e2e, dashboard
api_test.ts, jupyter-web-app unit tests); here the whole chain is one
test so a route/prefix/auth regression in any hop fails loudly.
"""

import json
import urllib.error
import urllib.parse
import urllib.request

import pytest

from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
from kubeflow_tpu.pipelines.api_server import PipelineAPIServer
from kubeflow_tpu.webapps.access_management import AccessManagementServer
from kubeflow_tpu.webapps.dashboard import DashboardServer
from kubeflow_tpu.webapps.gatekeeper import Gatekeeper, GatekeeperServer
from kubeflow_tpu.webapps.ingress import (AuthIngress, ExtAuthzVerifier,
                                          Route)
from kubeflow_tpu.webapps.jupyter import JupyterWebApp


class _NoRedirect(urllib.request.HTTPErrorProcessor):
    def http_response(self, request, response):
        return response


_OPENER = urllib.request.build_opener(_NoRedirect)


def fetch(url, cookie=None, data=None, method=None):
    req = urllib.request.Request(url, data=data, method=method)
    if cookie:
        req.add_header("Cookie", cookie)
    if data is not None and not method:
        req.add_header("Content-Type", "application/json")
    with _OPENER.open(req, timeout=10) as resp:
        return resp.status, resp.read(), dict(resp.headers)


@pytest.fixture
def stack():
    """cluster + dashboard + jupyter + gatekeeper behind ONE ingress."""
    cluster = FakeCluster()
    cluster.add_tpu_slice_nodes("v5e-8")
    cluster.create({"apiVersion": "v1", "kind": "Namespace",
                    "metadata": {"name": "kubeflow"}})
    mgr = Manager(cluster)
    mgr.add(TrainingJobReconciler("TPUJob"))
    servers = []

    def up(s):
        s.start()
        servers.append(s)
        return s

    dash = up(DashboardServer(cluster))
    jupyter = up(JupyterWebApp(cluster, prefix="jupyter"))
    kfam = up(AccessManagementServer(cluster))
    pipeline = up(PipelineAPIServer(cluster, prefix="pipeline"))
    gate = up(GatekeeperServer(Gatekeeper(username="admin", password="pw")))
    ingress = up(AuthIngress(
        ExtAuthzVerifier(auth_url=f"http://127.0.0.1:{gate.port}/auth",
                         login_path="/login"),
        routes=[Route("/", f"127.0.0.1:{dash.port}"),
                Route("/jupyter/", f"127.0.0.1:{jupyter.port}"),
                Route("/kfam/", f"127.0.0.1:{kfam.port}"),
                Route("/pipeline/", f"127.0.0.1:{pipeline.port}"),
                Route("/login", f"127.0.0.1:{gate.port}"),
                Route("/logout", f"127.0.0.1:{gate.port}")],
        public_prefixes=("/login", "/logout")))
    base = f"http://127.0.0.1:{ingress.port}"
    yield cluster, mgr, base
    for s in reversed(servers):
        s.stop()


def test_login_dashboard_spawn_runs_flow(stack):
    cluster, mgr, base = stack

    # 1. unauthenticated dashboard → 302 to login with the rd param
    status, _, headers = fetch(f"{base}/")
    assert status == 302
    assert headers["Location"] == "/login?rd=%2F"

    # 2. the login page serves THROUGH the ingress; the form POST sets
    # the session cookie and 303s back to the destination
    status, page, _ = fetch(f"{base}/login?rd=%2F")
    assert status == 200 and b"password" in page
    status, _, headers = fetch(
        f"{base}/login", data=b"username=admin&password=pw&rd=%2F",
        method="POST")
    assert status == 303 and headers["Location"] == "/"
    cookie = headers["Set-Cookie"].split(";")[0]

    # 3. dashboard shell + SPA bundle load with the cookie
    status, page, _ = fetch(f"{base}/", cookie)
    assert status == 200 and b'script src="app.js"' in page
    status, bundle, _ = fetch(f"{base}/app.js", cookie)
    assert status == 200 and b"viewRuns" in bundle

    # 4. the notebooks view iframes /jupyter/ — spawner shell + bundle
    # resolve through the ingress prefix
    status, page, _ = fetch(f"{base}/jupyter/", cookie)
    assert status == 200 and b"spawn-form" in page
    status, bundle, _ = fetch(f"{base}/jupyter/app.js", cookie)
    assert status == 200 and b"workspaceVolume" in bundle

    # 5. spawn a TPU notebook exactly as the form does; the Notebook CR
    # and its workspace PVC land in the cluster
    payload = json.dumps({
        "name": "bench-nb", "cpu": "2", "memory": "4Gi",
        "tpu": "2x2 (4 chips)",
        "workspaceVolume": {"size": "10Gi", "create": True},
    }).encode()
    status, body, _ = fetch(
        f"{base}/jupyter/api/namespaces/kubeflow/notebooks", cookie,
        data=payload)
    assert status == 200
    assert json.loads(body)["notebook"]["name"] == "bench-nb"
    nb = cluster.get("kubeflow.org/v1alpha1", "Notebook", "kubeflow",
                     "bench-nb")
    limits = nb["spec"]["template"]["spec"]["containers"][0][
        "resources"]["limits"]
    assert limits["google.com/tpu"] == 4
    cluster.get("v1", "PersistentVolumeClaim", "kubeflow",
                "workspace-bench-nb")

    # the spawner list shows it
    status, body, _ = fetch(
        f"{base}/jupyter/api/namespaces/kubeflow/notebooks", cookie)
    assert [n["name"] for n in json.loads(body)["notebooks"]] == ["bench-nb"]

    # 6. a training job reconciles and appears in the runs panel
    cluster.create({
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "train", "namespace": "kubeflow"},
        "spec": {"replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [
                {"name": "jax", "image": "t:v1"}]}}}}},
    })
    for _ in range(4):
        mgr.run_pending()
        cluster.tick()
    mgr.run_pending()
    status, body, _ = fetch(f"{base}/api/runs/kubeflow", cookie)
    runs = {r["name"]: r for r in json.loads(body)}
    assert runs["train"]["kind"] == "TPUJob"
    assert runs["train"]["phase"] in ("Running", "Created")

    # 7. overview data the stat tiles read
    status, body, _ = fetch(f"{base}/api/tpu/slices", cookie)
    slices = json.loads(body)
    assert sum(p["chips"] for p in slices) == 8

    # 8. env-info carries the ingress-authenticated identity + platform
    # (the sidebar footer's data): the ExtAuthz identity is minted by the
    # ingress, never taken from the client
    status, body, _ = fetch(f"{base}/api/env-info", cookie)
    env = json.loads(body)
    assert status == 200 and env["user"]["email"] == "admin"
    assert env["platform"]["kubeflowVersion"]

    # 8b. the pipelines view's API resolves through the ingress: submit a
    # run with an inline workflow spec, and the runs list shows it
    run_spec = json.dumps({
        "name": "ui-run", "namespace": "kubeflow",
        "workflow": {"spec": {"entrypoint": "main", "templates": [
            {"name": "main", "steps": [[{"name": "s1",
                                         "template": "noop"}]]},
            {"name": "noop", "container": {"image": "t:v1",
                                           "command": ["true"]}},
        ]}},
    }).encode()
    status, body, _ = fetch(f"{base}/pipeline/apis/v1beta1/runs", cookie,
                            data=run_spec)
    assert status == 200, body
    status, body, _ = fetch(
        f"{base}/pipeline/apis/v1beta1/runs?namespace=kubeflow", cookie)
    assert status == 200
    assert "ui-run" in [r["name"] for r in json.loads(body)["runs"]]
    status, body, _ = fetch(f"{base}/pipeline/apis/v1beta1/jobs", cookie)
    assert status == 200 and json.loads(body)["jobs"] == []

    # 9. contributors flow exactly as the SPA drives it: add through the
    # ingress-mounted KFAM app, list, remove
    binding = json.dumps({
        "user": {"kind": "User", "name": "alice@example.com"},
        "referredNamespace": "kubeflow",
        "roleRef": {"kind": "ClusterRole", "name": "kubeflow-edit"},
    }).encode()
    status, body, _ = fetch(f"{base}/kfam/v1/bindings", cookie, data=binding)
    assert status == 200
    status, body, _ = fetch(f"{base}/kfam/v1/bindings?namespace=kubeflow",
                            cookie)
    users = [b["user"]["name"] for b in json.loads(body)["bindings"]]
    assert users == ["alice@example.com"]
    status, _, _ = fetch(f"{base}/kfam/v1/bindings", cookie, data=binding,
                         method="DELETE")
    assert status == 200
    status, body, _ = fetch(f"{base}/kfam/v1/bindings?namespace=kubeflow",
                            cookie)
    assert json.loads(body)["bindings"] == []

    # 10. logout revokes the session: the dashboard bounces to login again
    fetch(f"{base}/logout", cookie)
    status, _, headers = fetch(f"{base}/", cookie)
    assert status == 302 and headers["Location"].startswith("/login")


def test_jupyter_prefix_serves_bare_paths_too(stack):
    # direct (non-ingress) access must keep working: the prefix is
    # additive, not a rebase
    cluster, _, base = stack
    jupyter = JupyterWebApp(cluster, prefix="jupyter")
    jupyter.start()
    try:
        d = f"http://127.0.0.1:{jupyter.port}"
        for path in ("/api/config", "/jupyter/api/config"):
            with urllib.request.urlopen(d + path, timeout=10) as r:
                assert json.loads(r.read())["images"]
    finally:
        jupyter.stop()
