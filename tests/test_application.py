"""Application aggregation controller (r2 verdict #6): selected component
statuses roll up into the Application's Ready condition — the native
replacement for the jsonnetd sync hook
(kubeflow/application/application.libsonnet:213-228)."""

from __future__ import annotations

import pytest

from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.controllers.application import (APPLICATION_API_VERSION,
                                                  APPLICATION_KIND,
                                                  ApplicationReconciler)
from kubeflow_tpu.controllers.runtime import Manager


def app_manifest(name="kf-app", ns="kubeflow", kinds=None, labels=None):
    return {
        "apiVersion": APPLICATION_API_VERSION, "kind": APPLICATION_KIND,
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "selector": {"matchLabels": labels or {"app.kubernetes.io/part-of": name}},
            "componentKinds": kinds or [{"group": "apps", "kind": "Deployment"},
                                        {"group": "", "kind": "Service"}],
        },
    }


def deployment(name, ns="kubeflow", labels=None, ready=0, want=1):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": name, "namespace": ns, "labels": labels or {}},
        "spec": {"replicas": want,
                 "selector": {"matchLabels": {"app": name}},
                 "template": {"metadata": {"labels": {"app": name}},
                              "spec": {"containers": [
                                  {"name": "c", "image": "x"}]}}},
        "status": {"readyReplicas": ready},
    }


def service(name, ns="kubeflow", labels=None):
    return {
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": name, "namespace": ns, "labels": labels or {}},
        "spec": {"ports": [{"port": 80}]},
    }


@pytest.fixture
def env():
    cluster = FakeCluster(auto_schedule=False, auto_run=False)
    mgr = Manager(cluster)
    mgr.add(ApplicationReconciler())
    yield cluster, mgr
    for c in mgr.controllers:
        c.stop()


def drive(mgr, rounds=3):
    for _ in range(rounds):
        mgr.run_pending()


def get_app(cluster, name="kf-app"):
    return cluster.get(APPLICATION_API_VERSION, APPLICATION_KIND,
                       "kubeflow", name)


def ready_condition(app):
    for c in app.get("status", {}).get("conditions", []):
        if c["type"] == "Ready":
            return c
    return None


class TestApplicationAggregation:
    LABELS = {"app.kubernetes.io/part-of": "kf-app"}

    def test_no_components_not_ready(self, env):
        cluster, mgr = env
        cluster.create(app_manifest())
        drive(mgr)
        cond = ready_condition(get_app(cluster))
        assert cond["status"] == "False"

    def test_ready_flips_with_child_health(self, env):
        cluster, mgr = env
        cluster.create(app_manifest())
        cluster.create(deployment("dash", labels=self.LABELS, ready=0))
        cluster.create(service("dash", labels=self.LABELS))
        drive(mgr)
        app = get_app(cluster)
        assert ready_condition(app)["status"] == "False"
        comps = {(c["kind"], c["name"]): c
                 for c in app["status"]["components"]}
        assert comps[("Deployment", "dash")]["status"] == "NotReady"
        assert comps[("Service", "dash")]["status"] == "Ready"
        assert app["status"]["componentsReady"] == "1/2"  # service ready

        # deployment becomes healthy → Ready flips True via the mapped watch
        dep = cluster.get("apps/v1", "Deployment", "kubeflow", "dash")
        dep["status"]["readyReplicas"] = 1
        cluster.update_status(dep)
        drive(mgr)
        app = get_app(cluster)
        assert ready_condition(app)["status"] == "True"
        assert app["status"]["componentsReady"] == "2/2"

        # and back down when health degrades
        dep = cluster.get("apps/v1", "Deployment", "kubeflow", "dash")
        dep["status"]["readyReplicas"] = 0
        cluster.update_status(dep)
        drive(mgr)
        assert ready_condition(get_app(cluster))["status"] == "False"

    def test_selector_scopes_components(self, env):
        cluster, mgr = env
        cluster.create(app_manifest())
        cluster.create(deployment("mine", labels=self.LABELS, ready=1))
        cluster.create(deployment("other",
                                  labels={"app.kubernetes.io/part-of": "x"},
                                  ready=0))
        drive(mgr)
        app = get_app(cluster)
        names = [c["name"] for c in app["status"]["components"]]
        assert names == ["mine"]
        assert ready_condition(app)["status"] == "True"

    def test_two_apps_isolated(self, env):
        cluster, mgr = env
        cluster.create(app_manifest("a1", labels={"part": "a1"}))
        cluster.create(app_manifest("a2", labels={"part": "a2"}))
        cluster.create(deployment("d1", labels={"part": "a1"}, ready=1))
        cluster.create(deployment("d2", labels={"part": "a2"}, ready=0))
        drive(mgr)
        assert ready_condition(get_app(cluster, "a1"))["status"] == "True"
        assert ready_condition(get_app(cluster, "a2"))["status"] == "False"

    def test_deleted_app_noop(self, env):
        cluster, mgr = env
        cluster.create(app_manifest())
        drive(mgr)
        cluster.delete(APPLICATION_API_VERSION, APPLICATION_KIND,
                       "kubeflow", "kf-app")
        drive(mgr)  # must not raise
