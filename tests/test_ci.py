"""L6 CI plumbing (r2 verdict #10): prow-style path→workflow selection
over ci_config.yaml, and the image-release workflow running on the
engine."""

from __future__ import annotations

import pytest

from kubeflow_tpu.workflows.ci import (CIEntry, load_ci_config,
                                       release_workflow,
                                       repo_ci_config_path,
                                       select_workflows)


@pytest.fixture(scope="module")
def entries():
    return load_ci_config(repo_ci_config_path())


class TestPathMapping:
    def test_config_loads(self, entries):
        names = {e.name for e in entries}
        assert {"unit_tests", "datapipe_native", "manifests_golden",
                "release_images", "nightly_bench_matrix"} <= names

    def test_model_change_selects_bench_and_unit(self, entries):
        selected = {e.name for e in select_workflows(
            ["kubeflow_tpu/models/resnet.py"], entries)}
        assert "unit_tests" in selected
        assert "bench_smoke" in selected
        assert "datapipe_native" not in selected

    def test_native_change_selects_datapipe(self, entries):
        selected = {e.name for e in select_workflows(
            ["native/datapipe/datapipe.cc"], entries)}
        assert selected == {"datapipe_native"}

    def test_docs_change_selects_nothing(self, entries):
        assert select_workflows(["README.md", "PERF.md"], entries) == []

    def test_postsubmit_trigger_class(self, entries):
        pre = select_workflows(["kubeflow_tpu/api/k8s.py"], entries)
        post = select_workflows(["kubeflow_tpu/api/k8s.py"], entries,
                                trigger="postsubmit")
        assert all(e.trigger == "presubmit" for e in pre)
        assert {e.name for e in post} == {"release_images",
                                          "unit_tests_slow"}
        by_name = {e.name: e for e in post}
        assert by_name["release_images"].params["registry"].startswith(
            "ghcr.io")
        # the tier split: a control-plane smoke gate (no slow, no JAX
        # compiles), the full fast presubmit, and the slow postsubmit
        # companion running exactly the slow marker
        assert by_name["unit_tests_slow"].params["pytest_args"] == "-m slow"
        pre_by_name = {e.name: e for e in pre}
        assert pre_by_name["unit_tests"].params["pytest_args"] == \
            "-m 'not slow'"
        assert pre_by_name["unit_tests_smoke"].params["pytest_args"] == \
            "-m 'not slow and not compute'"

    def test_periodic_ignores_diff(self, entries):
        sel = select_workflows([], entries, trigger="periodic")
        assert {e.name for e in sel} == {"nightly_bench_matrix"}

    def test_bad_trigger_rejected(self, tmp_path):
        bad = tmp_path / "ci.yaml"
        bad.write_text("workflows:\n- name: x\n  trigger: nightly\n")
        with pytest.raises(ValueError, match="trigger"):
            load_ci_config(str(bad))

    def test_glob_crosses_directories(self):
        e = CIEntry(name="x", workflow="x",
                    include=["kubeflow_tpu/**"])
        assert e.matches("kubeflow_tpu/a/b/c.py")
        assert not e.matches("tests/a.py")


class TestReleaseWorkflow:
    def test_shape_and_dag_order(self):
        wf = release_workflow("manager", "v0.2.0")
        assert wf["kind"] == "Workflow"
        tmpl = {t["name"]: t for t in wf["spec"]["templates"]}
        tasks = {t["name"]: t for t in tmpl["release"]["dag"]["tasks"]}
        assert tasks["test"]["dependencies"] == ["checkout"]
        assert tasks["build"]["dependencies"] == ["test"]
        assert tasks["push"]["dependencies"] == ["build"]
        # every step carries the CI wall-time budget (SURVEY §6)
        for name in ("checkout", "test", "build", "push"):
            assert tmpl[name]["activeDeadlineSeconds"] <= 3000
        params = {p["name"]: p["value"]
                  for p in wf["spec"]["arguments"]["parameters"]}
        assert params["image"] == "ghcr.io/kubeflow-tpu/manager:v0.2.0"

    def test_runs_on_engine(self):
        """The release DAG executes end-to-end on the workflow engine."""
        from kubeflow_tpu.api import k8s
        from kubeflow_tpu.cluster import FakeCluster
        from kubeflow_tpu.controllers.runtime import Manager
        from kubeflow_tpu.workflows.engine import WorkflowReconciler

        cluster = FakeCluster()
        cluster.add_node("ci-0", {"cpu": 96, "memory": 2 ** 36})
        mgr = Manager(cluster)
        mgr.add(WorkflowReconciler())
        wf = release_workflow("manager", "v0.2.0", namespace="kubeflow")
        cluster.create(wf)
        for _ in range(16):
            mgr.run_pending()
            cluster.tick()
            for pod in cluster.list("v1", "Pod", "kubeflow"):
                if pod.get("status", {}).get("phase") == "Running":
                    cluster.set_pod_phase("kubeflow", k8s.name_of(pod),
                                          "Succeeded")
        done = cluster.get("argoproj.io/v1alpha1", "Workflow", "kubeflow",
                           wf["metadata"]["name"])
        assert done["status"]["phase"] == "Succeeded"
        nodes = done["status"]["nodes"]
        assert {n for n in nodes} >= {"checkout", "test", "build", "push"}
        for c in mgr.controllers:
            c.stop()
